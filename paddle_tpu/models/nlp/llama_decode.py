"""Compiled KV-cache generation for Llama.

~ the reference's generative-inference flagship
(fused_multi_transformer_op.cu: stacked weights + in-place KV cache, one
kernel per decode step). TPU-native: prefill captures per-layer K/V into
a (L, B, kv_heads, max_len, head_dim) functional cache; each decode step
is ONE jitted program (lax.scan over the stacked layer weights) that
attends a single query position against the cache and writes its K/V at
`pos` via dynamic_update_slice — O(S) per token instead of the O(S²)
recompute of the eager `LlamaForCausalLM.generate`.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...jax_compat import device_put_sharded, make_mesh
from .llama import LlamaConfig, LlamaForCausalLM, apply_rotary
from .llama_functional import _rms, split_params  # noqa: F401 (re-export)
from .llama_functional import stack_layers, unstack_layers  # noqa: F401


def _stack_apply(body, x, stacked, scan_layers: bool = True):
    """Run ``body(carry, per_layer) -> (carry, ys)`` over the leading L
    axis of every leaf in ``stacked`` (the stack_layers convention shared
    with the training path).

    ``scan_layers=True`` lowers the layer body ONCE as a ``lax.scan`` —
    program size is O(1) in depth, which is what lets the two-model
    speculative program compile at real model sizes (the unrolled form
    is ~L x larger and broke the remote compiler at 0.44B).
    ``scan_layers=False`` python-unrolls L copies of the body into the
    jaxpr: the parity/debug fallback the scan path is tested token-exact
    against (and the shape a per-layer-heterogeneous model would need).
    """
    if scan_layers:
        return jax.lax.scan(body, x, stacked)
    L = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    ys = []
    for i in range(L):
        x, y = body(x, jax.tree_util.tree_map(lambda a: a[i], stacked))
        ys.append(y)
    return x, jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *ys)


def _mm(x, w):
    """Matmul against a weight that may be int8-quantized.

    Plain array -> x @ w. Tuple (w_q int8 (in,out), scale f32 (out,)) ->
    the shared int8 GEMM (quantization.int8.int8_matmul): 2x the bf16
    dot rate on v5e-class MXUs and half the weight HBM bytes — decode at
    small batch is weight-bandwidth-bound.
    """
    if not isinstance(w, tuple):
        return x @ w
    from ...quantization.int8 import int8_matmul
    return int8_matmul(x, w[0], w[1])


def _quantize_weights(tree, keys):
    """Per-output-channel int8 for the named (..., in, out) weights:
    value -> (int8 data, f32 scale over the 'in' axis)."""
    from ...quantization.int8 import quantize_stacked_jnp
    out = dict(tree)
    for k in keys:
        if tree.get(k) is not None:
            out[k] = quantize_stacked_jnp(tree[k])
    return out


_PROJ_KEYS = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
              "self_attn.v_proj.weight", "self_attn.o_proj.weight",
              "mlp.gate_proj.weight", "mlp.up_proj.weight",
              "mlp.down_proj.weight")


# --- multi-adapter LoRA (batched multi-model serving) ----------------------

@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """Multi-adapter LoRA layout for the paged serving decode path:
    a device-resident ADAPTER BANK of ``n_slots`` stacked low-rank
    delta sets over the q/v attention projections (the classic LoRA
    target pair), applied per batch row by slot index — the
    S-LoRA / Punica batched-multi-adapter design riding PR 1's
    weights-as-args invariant: the bank and the per-row index vector
    are jit INPUTS, so one fixed-shape ``decode_n`` program serves any
    mix of adapters and admission churn never recompiles.

    Slot 0 is the reserved IDENTITY (all-zero deltas): ``adapter=None``
    rows are routed through it and their delta is an exact float zero
    — token-for-token the base model. ``rank`` is the low-rank width
    ``r`` (delta = ``(h @ A) @ B * scale``); ``scale`` is the merged
    ``alpha / r`` multiplier applied at serve time."""

    n_slots: int = 4
    rank: int = 4
    scale: float = 1.0

    def __post_init__(self):
        if self.n_slots < 2:
            raise ValueError("LoRAConfig needs n_slots >= 2 (slot 0 "
                             "is the reserved identity)")
        if self.rank < 1:
            raise ValueError("LoRAConfig rank must be >= 1")


def as_lora_config(lora) -> "LoRAConfig | None":
    """Normalize the ``lora=`` argument: None stays None, a
    ``(n_slots, rank)`` tuple becomes a LoRAConfig, a LoRAConfig
    passes through."""
    if lora is None or isinstance(lora, LoRAConfig):
        return lora
    if isinstance(lora, tuple) and len(lora) == 2:
        return LoRAConfig(n_slots=int(lora[0]), rank=int(lora[1]))
    raise ValueError(f"lora {lora!r}: pass None, (n_slots, rank), or "
                     "a LoRAConfig")


LORA_KEYS = ("q_A", "q_B", "v_A", "v_B")


# --- constrained decoding (grammar/JSON-schema guided generation) ----------

@dataclasses.dataclass(frozen=True)
class GrammarConfig:
    """Constrained-decoding layout for the paged serving decode path:
    a device-resident GRAMMAR BANK of ``n_slots * max_states`` packed
    uint32 allow-bitmask rows, indexed per batch row by a flat
    ``slot * max_states + state`` id — the same per-row-state-as-jit-
    data mechanism the adapter bank (PR 12) and the quantized page
    tier (PR 14) ride: the bank and the id vector are jit INPUTS, so
    one fixed-shape ``decode_n`` program serves any mix of schemas
    and grammar churn never recompiles.

    Slot 0 is the reserved ALL-ALLOW identity (every bit set): free
    rows carry flat id 0 and their masked logits are exactly the base
    logits — token-for-token the unconstrained model. ``max_states``
    bounds one automaton's DFA size (compilation refuses larger
    schemas loudly)."""

    n_slots: int = 4
    max_states: int = 64

    def __post_init__(self):
        if self.n_slots < 2:
            raise ValueError("GrammarConfig needs n_slots >= 2 "
                             "(slot 0 is the reserved all-allow "
                             "identity)")
        if self.max_states < 2:
            raise ValueError("GrammarConfig max_states must be >= 2")


def as_grammar_config(grammar) -> "GrammarConfig | None":
    """Normalize the ``grammar=`` argument: None stays None, a
    ``(n_slots, max_states)`` tuple becomes a GrammarConfig, a
    GrammarConfig passes through."""
    if grammar is None or isinstance(grammar, GrammarConfig):
        return grammar
    if isinstance(grammar, tuple) and len(grammar) == 2:
        return GrammarConfig(n_slots=int(grammar[0]),
                             max_states=int(grammar[1]))
    raise ValueError(f"grammar {grammar!r}: pass None, (n_slots, "
                     "max_states), or a GrammarConfig")


def grammar_bank_hooks(vocab_size: int, grammar: "GrammarConfig",
                       tp: "TPConfig | None" = None):
    """The grammar-cache device hooks: ``(init_grammar_bank,
    upload_grammar)``.

    ``init_grammar_bank()`` builds the ``(n_slots * max_states,
    ceil(vocab/32))`` uint32 bank with slot 0's whole block all-ones
    (the all-allow identity every free row indexes at flat id 0) and
    the rest zero until uploaded. Under ``tp`` the bank is placed
    REPLICATED on the mesh (a bank is a few KB — replication costs
    nothing and every shard masks its own logits copy identically).

    ``upload_grammar(bank, slot, compiled)`` writes one compiled
    automaton's packed per-state masks into the slot's block
    (functional ``.at[...].set`` — the returned bank REBINDS), zeroing
    the block's unused tail so a recycled slot can never leak a
    larger predecessor's rows. ``compiled`` is a
    ``serving.grammar.CompiledGrammar``-shaped object (``n_states``,
    ``masks``)."""
    words = (int(vocab_size) + 31) // 32
    ms, ns = grammar.max_states, grammar.n_slots

    def init_grammar_bank():
        bank = np.zeros((ns * ms, words), np.uint32)
        bank[:ms] = np.uint32(0xFFFFFFFF)
        bank = jnp.asarray(bank)
        if tp is not None:
            bank = device_put_sharded(bank, tp.build_mesh())
        return bank

    def upload_grammar(bank, slot, compiled):
        n = int(compiled.n_states)
        if n > ms:
            raise ValueError(f"grammar compiles to {n} states > "
                             f"max_states {ms}")
        masks = np.asarray(compiled.masks, np.uint32)
        if masks.shape != (n, words):
            raise ValueError(f"grammar masks have shape {masks.shape},"
                             f" bank rows want (*, {words}) (vocab "
                             "mismatch?)")
        block = np.zeros((ms, words), np.uint32)
        block[:n] = masks
        return bank.at[slot * ms:(slot + 1) * ms].set(
            jnp.asarray(block))

    return init_grammar_bank, upload_grammar


def _bgmv(h, A, B_, ids):
    """Batched gather matvec (Punica's BGMV): per-row low-rank delta
    ``(h @ A[row]) @ B[row]``. ``h`` (B, T, H); ``A`` (n_slots, H, r);
    ``B_`` (n_slots, r, out); ``ids`` (B,) int slot indices. The
    gather is by row SEGMENT — every row of a same-adapter group reads
    the same bank slice (the engine's admission ordering groups
    adapter-sharers adjacently) — and the whole thing is fixed-shape:
    slot indices are data, so adapter churn never recompiles."""
    Ar = jnp.take(A, ids, axis=0)          # (B, H, r)
    Br = jnp.take(B_, ids, axis=0)         # (B, r, out)
    t = jnp.einsum("bth,bhr->btr", h, Ar)
    return jnp.einsum("btr,bro->bto", t, Br)


def synthesize_lora_deltas(cfg: LlamaConfig, rank: int, seed: int = 0,
                           init_scale: float = 0.02) -> dict:
    """One seeded host-resident LoRA delta set for ``cfg``'s decode
    path, the layout ``llama_paged_decode_factory(lora=...)``'s
    ``upload_adapter`` hook consumes: ``q_A``/``v_A`` (L, H, r) and
    ``q_B``/``v_B`` (L, r, out) numpy float32. Both factors are drawn
    nonzero (unlike training-init LoRA, where B starts at zero — a
    zero delta would make every adapter the base model and parity
    tests vacuous). Deterministic in (cfg, rank, seed)."""
    rng = np.random.default_rng(seed)
    L = cfg.num_hidden_layers
    H = cfg.hidden_size
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = H // nh

    def draw(*shape):
        return (rng.standard_normal(shape) * init_scale).astype(
            np.float32)

    return {"q_A": draw(L, H, rank), "q_B": draw(L, rank, nh * hd),
            "v_A": draw(L, H, rank), "v_B": draw(L, rank, nkv * hd)}


def lora_bank_hooks(cfg: LlamaConfig, lora: "LoRAConfig", dtype,
                    tp: "TPConfig | None" = None):
    """The adapter-cache device hooks for a llama decode path:
    ``(init_adapter_bank, upload_adapter)``.

    ``init_adapter_bank()`` builds the all-zero device bank — per
    LoRA key a ``(L, n_slots, ...)`` array stacked layer-first so it
    scans with the layer weights; slot 0 stays zero forever (the
    identity every ``adapter=None`` row decodes through). Under
    ``tp`` the bank is placed REPLICATED on the mesh (rank is tiny —
    a few KB per adapter — so replication costs nothing and the
    delta add simply reshards into the column-parallel q/v layout).

    ``upload_adapter(bank, slot, deltas)`` is the paced host->device
    upload: a functional ``.at[:, slot].set`` per key (the returned
    bank REBINDS — sharding and every other slot's content
    preserved). ``deltas`` is a ``synthesize_lora_deltas``-shaped
    host tree: ``q_A``/``v_A`` (L, H, r), ``q_B``/``v_B``
    (L, r, out)."""
    L = cfg.num_hidden_layers
    H = cfg.hidden_size
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = H // nh
    r, ns = lora.rank, lora.n_slots
    shapes = {"q_A": (L, ns, H, r), "q_B": (L, ns, r, nh * hd),
              "v_A": (L, ns, H, r), "v_B": (L, ns, r, nkv * hd)}

    def init_adapter_bank():
        bank = {k: jnp.zeros(s, dtype) for k, s in shapes.items()}
        if tp is not None:
            bank = device_put_sharded(bank, tp.build_mesh())
        return bank

    def upload_adapter(bank, slot, deltas):
        for k in LORA_KEYS:
            if k not in deltas:
                raise ValueError(f"adapter delta set missing {k!r} "
                                 f"(needs {LORA_KEYS})")
            want = shapes[k][:1] + shapes[k][2:]
            got = tuple(np.asarray(deltas[k]).shape)
            if got != want:
                raise ValueError(f"adapter delta {k} has shape {got}, "
                                 f"bank slot wants {want} (rank/model "
                                 "mismatch?)")
        return {k: bank[k].at[:, slot].set(
            jnp.asarray(np.asarray(deltas[k]), bank[k].dtype))
            for k in LORA_KEYS}

    return init_adapter_bank, upload_adapter


# --- speculative serving (draft + target over ONE paged pool) --------------

@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Adaptive speculative-decode policy for the serving engine.

    ``n_draft`` is the draft window: each spec round proposes that
    many tokens (one draft walk) and verifies them in ONE batched
    target block — greedy acceptance keeps every emitted token
    EXACTLY the target model's greedy token, so speculation changes
    latency, never content.

    The ADAPTIVE half is per-request + per-run:

    - eligibility (``Policy.spec_route``): a request decodes
      speculatively only when ``priority <= max_priority`` AND its
      deadline is loose (``deadline_ms`` unset or >=
      ``loose_deadline_ms``) — tight/high-priority traffic keeps the
      plain fixed-latency decode path;
    - acceptance floor: the engine EWMAs the measured per-turn
      acceptance (accepted/proposed, ``ewma_alpha``); once at least
      ``min_rounds`` spec TURNS (EWMA samples — a busy turn's eight
      rows are still one sample) are in evidence and the EWMA sits
      below ``accept_floor``, the route LATCHES to plain decode for
      the rest of the run (draft compute that mostly misses is pure
      waste);
    - overload fallback (``overload_fallback``): while a
      page-severity SLO incident delivered through
      ``QoSScheduler.note_incident`` (e.g. a ``BurnRateRule`` firing)
      stays open, spec rows decode plain — draft compute is spent
      exactly when capacity is scarce, so overload is the moment to
      stop spending it. The route re-enables when the incident
      closes.

    Every flip is logged on the virtual clock with the rule that
    fired (``ServeResult.spec_stats["flips"]``)."""

    n_draft: int = 4
    accept_floor: float = 0.35
    ewma_alpha: float = 0.25
    min_rounds: int = 8
    max_priority: int = 0
    loose_deadline_ms: float = 8000.0
    overload_fallback: bool = True

    def __post_init__(self):
        if self.n_draft < 1:
            raise ValueError("SpecConfig n_draft must be >= 1")
        if not 0.0 <= self.accept_floor <= 1.0:
            raise ValueError("accept_floor is an acceptance fraction "
                             "in [0, 1]")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if self.min_rounds < 1:
            raise ValueError("min_rounds must be >= 1")
        if self.loose_deadline_ms < 0:
            raise ValueError("loose_deadline_ms must be >= 0")


def as_spec_config(spec) -> "SpecConfig | None":
    """Normalize the ``spec=`` argument: None/False stays off, True
    is the stock SpecConfig (bool checked FIRST — ``True`` is an int
    in python, and silently reading it as ``n_draft=1`` would cripple
    the draft window), an int becomes a SpecConfig with that draft
    window, a SpecConfig passes through."""
    if isinstance(spec, bool):
        return SpecConfig() if spec else None
    if spec is None or isinstance(spec, SpecConfig):
        return spec
    if isinstance(spec, int):
        return SpecConfig(n_draft=spec)
    raise ValueError(f"spec {spec!r}: pass None, True, an int "
                     "n_draft, or a SpecConfig")


def _write_positions(pool_l, kv, page_tables, positions, page_size):
    """kv (B, nkv, T, hd) written at PER-ROW absolute ``positions``
    (B, T) through the page tables — the speculative draft/verify
    write. Unlike ``_write_chunk`` (page-aligned) or ``_write_token``
    (one slot), spec blocks start at each row's current length, so
    every (row, t) scatters to its own (page, offset). Positions of
    inactive rows resolve through page-table row 0 into the reserved
    padding page (the same junk-write discipline empty decode slots
    ride)."""
    pages = jnp.take_along_axis(page_tables, positions // page_size, 1)
    offs = positions % page_size
    if isinstance(pool_l, tuple):
        data, sc = pool_l
        qd, s = _q8(kv)
        return (data.at[:, pages, offs].set(
                    jnp.transpose(qd, (1, 0, 2, 3))),
                sc.at[:, pages, offs].set(jnp.transpose(s, (1, 0, 2))))
    return pool_l.at[:, pages, offs].set(
        jnp.transpose(kv, (1, 0, 2, 3)).astype(pool_l.dtype))


def build_spec_step(cfg_t: LlamaConfig, cfg_d: LlamaConfig,
                    page_size: int, scan_layers: bool = True):
    """ONE compiled speculative round over the paged pool, batched
    across decode slots: the draft consumes ``[prev, tok]`` (two
    positions — re-consuming position len-1 rewrites identical K/V
    and guarantees the draft cache has no hole after a
    fully-accepted round, the PR-1 two-token-feed trick) then walks
    ``k-1`` more greedy steps as an in-jit scan; the target verifies
    ``[tok, d_0..d_{k-1}]`` in ONE (k+1)-position block through its
    pool. Per-row positions are data (``lengths``), so rows at
    different depths — and rows routed PLAIN this turn, riding along
    as length-0 page-0 rows — share the one fixed-shape program and
    admission churn never recompiles.

    Acceptance is the branch-free PR-1 arithmetic: ``n`` = length of
    the matching draft prefix, the candidate vector holds accepted
    drafts then the target's correction/bonus token, junk beyond
    ``n`` is overwritten by later rounds (the same
    overwrite-rollback invariant both pools use — K/V written for
    rejected proposals sits beyond the advanced length and the key
    masks never reach it).

    Both models' weights travel as ARGUMENTS (the PR-1
    weights-as-jit-args invariant — a closure capture would inline
    model-sized constants into the module); under TP the caller
    passes target weights sharded and draft weights replicated, and
    the program inherits the arg shardings unchanged.

    Returns a host shim ``spec_step(outer_t, layers_t, outer_d,
    layers_d, prev_tok, tok, page_tables, lengths, pools_t, pools_d,
    k) -> (accepted (B,), cand (B, k+1), pools_t', pools_d')`` whose
    inner jitted program is advertised via ``_jit_inner`` (the PR-4
    convention), so the engine's recompile detector and
    ``jit.compile`` trace instants see spec compiles."""

    def make_block(cfg):
        nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        hd = cfg.hidden_size // nh

        def block(outer, layers, tokens, pos, page_tables, pools):
            """tokens (B, T) at per-row absolute positions ``pos``
            (B, T): write K/V at those slots, attend causally over
            the whole pool, return (logits (B, T, V), pools')."""
            k_pools, v_pools = pools
            B, T = tokens.shape
            W = page_tables.shape[1]
            S = W * page_size
            x = jnp.take(outer["model.embed_tokens.weight"], tokens,
                         axis=0)
            key_ok = jnp.arange(S)[None, None, :] <= pos[:, :, None]
            mask = key_ok[:, None]

            def gather(pool):
                if isinstance(pool, tuple):
                    data, sc = pool
                    g = (data[:, page_tables].astype(jnp.float32)
                         * sc[:, page_tables][..., None])
                else:
                    g = pool[:, page_tables]
                return jnp.swapaxes(g, 0, 1).reshape(B, nkv, S, hd)

            def body(x, per_layer):
                lp, kp_l, vp_l = per_layer

                def attend(q, k, v):
                    kp = _write_positions(kp_l, k, page_tables, pos,
                                          page_size)
                    vp = _write_positions(vp_l, v, page_tables, pos,
                                          page_size)
                    return _attend(cfg, q,
                                   gather(kp).astype(q.dtype),
                                   gather(vp).astype(q.dtype),
                                   mask), (kp, vp)

                x, (kp, vp) = _layer_math(cfg, lp, x, pos, attend)
                return x, (kp, vp)

            x, (k_pools, v_pools) = _stack_apply(
                body, x, (layers, k_pools, v_pools), scan_layers)
            x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
            return _logits(cfg, outer, x), (k_pools, v_pools)

        return block

    block_t = make_block(cfg_t)
    block_d = make_block(cfg_d)

    def _step_body(outer_t, layers_t, outer_d, layers_d, prev_tok,
                   tok, page_tables, lengths, pools_t, pools_d, k):
        B = tok.shape[0]
        lens = lengths
        # draft: consume [prev, tok] at (len-1, len), emit d_0, then
        # walk k-1 more steps (in-jit scan — one traced draft block)
        feed = jnp.stack([prev_tok, tok], 1).astype(jnp.int32)
        pos0 = lens[:, None] + jnp.asarray([-1, 0])[None, :]
        lg, pools_d = block_d(outer_d, layers_d, feed, pos0,
                              page_tables, pools_d)
        cur = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)

        def dstep(carry, i):
            cur, pd = carry
            lg, pd = block_d(outer_d, layers_d, cur[:, None],
                             lens[:, None] + 1 + i, page_tables, pd)
            return (jnp.argmax(lg[:, -1], -1).astype(jnp.int32),
                    pd), cur

        (last_d, pools_d), ds = jax.lax.scan(
            dstep, (cur, pools_d), jnp.arange(k - 1))
        drafts = jnp.concatenate(
            [jnp.swapaxes(ds, 0, 1), last_d[:, None]], 1) \
            if k > 1 else last_d[:, None]                    # (B, k)
        # target verifies [tok, d_0..d_{k-1}] in ONE (k+1)-pos block
        blk = jnp.concatenate([tok[:, None], drafts], 1) \
            .astype(jnp.int32)
        pos_t = lens[:, None] + jnp.arange(k + 1)[None, :]
        lg_t, pools_t = block_t(outer_t, layers_t, blk, pos_t,
                                page_tables, pools_t)
        t = jnp.argmax(lg_t, -1).astype(jnp.int32)       # (B, k+1)
        matches = (drafts == t[:, :k]).astype(jnp.int32)
        n = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
        idx = jnp.arange(k + 1)[None, :]
        dpad = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], 1)
        cand = jnp.where(idx < n[:, None], dpad, t)
        return n, cand, pools_t, pools_d

    step = partial(jax.jit, static_argnums=(10,),
                   donate_argnums=(8, 9))(_step_body)

    def spec_step(outer_t, layers_t, outer_d, layers_d, prev_tok,
                  tok, page_tables, lengths, pools_t, pools_d, k):
        return step(outer_t, layers_t, outer_d, layers_d, prev_tok,
                    tok, page_tables, lengths, pools_t, pools_d, k)

    spec_step._jit_inner = (step,)
    return spec_step


# --- tensor parallelism (sharded decode weights + paged pool) --------------

@dataclasses.dataclass(frozen=True)
class TPConfig:
    """Tensor-parallel layout for the serving decode path: a 1-D named
    device mesh, attention heads and MLP hidden dims partitioned over
    ``axis``, everything else (embeddings, norms, lm head, page
    tables) replicated. Threaded into the decode/prefill factories —
    weights and pools are placed ONCE at load (NamedSharding;
    jax_compat.device_put_sharded) and every jitted call inherits the
    arg shardings, so the fixed-shape ``decode_n`` batches still never
    recompile across churn.

    ``hbm_budget_bytes_per_device``: optional per-device byte budget
    for weights + KV pool together; the factory measures the ACTUAL
    per-device resident bytes after placement and refuses loudly
    (MemoryError) when they exceed it — the "a model bigger than one
    chip serves only under TP" check the serving_tp gate exercises.
    """

    mesh_shape: tuple = (2,)
    axis: str = "tp"
    hbm_budget_bytes_per_device: int | None = None

    def __post_init__(self):
        shape = tuple(int(s) for s in self.mesh_shape)
        object.__setattr__(self, "mesh_shape", shape)
        if len(shape) != 1 or shape[0] < 1:
            raise ValueError(f"TPConfig mesh_shape {shape}: tensor "
                             "parallelism is a 1-D mesh (one named "
                             "axis)")

    @property
    def size(self) -> int:
        return self.mesh_shape[0]

    def build_mesh(self):
        return make_mesh(self.mesh_shape, (self.axis,))


def as_tp_config(tp) -> TPConfig | None:
    """Normalize the ``tp=`` argument: None stays None, an int becomes
    a 1-D TPConfig of that many devices, a TPConfig passes through."""
    if tp is None or isinstance(tp, TPConfig):
        return tp
    if isinstance(tp, int):
        return TPConfig(mesh_shape=(tp,))
    raise ValueError(f"tp {tp!r}: pass None, an int degree, or a "
                     "TPConfig")


def tp_layer_specs(axis: str = "tp") -> dict:
    """PartitionSpec args for the STACKED (L, in, out) decode layer
    weights: column-parallel q/k/v and MLP gate/up (output features —
    heads / hidden dims — split over ``axis``), row-parallel o_proj
    and down_proj (input features split; jit inserts the psum over the
    contraction), norms replicated (missing keys -> replicated in
    ``device_put_sharded``). The Megatron layout: one all-reduce per
    attention block, one per MLP, no resharding between them."""
    col = (None, None, axis)
    row = (None, axis, None)
    return {
        "self_attn.q_proj.weight": col,
        "self_attn.k_proj.weight": col,
        "self_attn.v_proj.weight": col,
        "self_attn.o_proj.weight": row,
        "mlp.gate_proj.weight": col,
        "mlp.up_proj.weight": col,
        "mlp.down_proj.weight": row,
    }


def tp_pool_spec(axis: str = "tp") -> tuple:
    """PartitionSpec args for the paged KV pools (L, Hkv, P, page,
    hd): page CONTENT splits by kv head over ``axis``; page ids,
    tables and lengths stay host-side and replicated (trailing dims
    unspecified = replicated, which also covers the int8 scale leaves'
    (L, Hkv, P, page) shape)."""
    return (None, axis)


def _validate_tp(cfg: LlamaConfig, tp: TPConfig):
    if tp.size > len(jax.devices()):
        raise ValueError(f"tp={tp.size} needs {tp.size} devices, have "
                         f"{len(jax.devices())}")
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    inter = cfg.intermediate_size
    for name, dim in (("attention heads", nh), ("kv heads", nkv),
                      ("mlp intermediate", inter)):
        if dim % tp.size:
            raise ValueError(
                f"tp={tp.size} does not divide {name} ({dim}) — the "
                "head/hidden partition would be ragged")


def tree_device_bytes(tree) -> int:
    """Resident bytes of ``tree``'s leaves on ONE device: a sharded
    leaf contributes one device's shard bytes (computed from the
    sharding's shard shape — metadata only, so a DONATED buffer that
    already died still answers), a replicated or unsharded leaf its
    full size — the per-device HBM footprint the TP capacity claims
    are judged on. Host (numpy) leaves count whole."""
    total = 0
    for a in jax.tree_util.tree_leaves(tree):
        sh = getattr(a, "sharding", None)
        if sh is not None and hasattr(sh, "shard_shape"):
            shard = sh.shard_shape(a.shape)
            total += int(np.prod(shard, dtype=np.int64)) \
                * a.dtype.itemsize
        else:
            total += int(getattr(a, "nbytes", np.asarray(a).nbytes))
    return total


def decode_need_bytes_per_device(outer, layers, pools) -> int:
    """THE per-device residency arithmetic for a decode factory:
    weights + KV pools, one device's share each. The factory's
    ``hbm_budget_bytes_per_device`` refusal, the bench's capacity
    demo and the tests all call THIS — three private copies could
    silently diverge and flip the refuses/serves verdict."""
    return (tree_device_bytes(outer) + tree_device_bytes(layers)
            + tree_device_bytes(pools))


# --- quantized KV page tier (kv_quant serving) ------------------------------

def kv_quant_page_bytes(cfg: "LlamaConfig", page_size: int,
                        dtype) -> tuple:
    """(full_precision, int8+scale) bytes ONE page costs across all
    layers, k+v — the per-page prices ``PagedKVCache.stored_bytes()``
    charges. A quantized slot stores head_dim int8 bytes plus one f32
    per-slot scale (the _q8 codec), so the int8 price is
    ``hd + 4`` bytes per slot vs ``hd * itemsize`` full precision."""
    L = cfg.num_hidden_layers
    nkv = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    slots = L * nkv * page_size
    fp = 2 * slots * hd * np.dtype(dtype).itemsize
    q = 2 * slots * (hd + 4)
    return fp, q


@jax.jit
def compact_kv_pages(pools, mask):
    """Quantize the masked pages of a PRESSURE-tier pool (functional):
    per-slot absmax int8 (``_q8``) written into the int8 arena, tier
    bits set. Fixed shape — ``mask`` is a (P,) bool jit INPUT, so any
    compaction batch reuses the one compiled program and compaction
    churn never recompiles. The full-precision slots of a compacted
    page are left in place but dead: every read goes through the tier
    mask, and the write path clears a page's tier bit in the same
    program that rewrites it."""
    (kf, kq, ks), (vf, vq, vs), tier = pools
    m5 = mask[None, None, :, None, None]
    m4 = mask[None, None, :, None]

    def one(fp, qd0, s0):
        qd, s = _q8(fp)
        return jnp.where(m5, qd, qd0), jnp.where(m4, s, s0)

    kq, ks = one(kf, kq, ks)
    vq, vs = one(vf, vq, vs)
    return (kf, kq, ks), (vf, vq, vs), tier | mask


def export_quant_pages(pools, page_ids):
    """Slice a PRESSURE pool's pages for a disaggregated handoff: both
    arenas AND the per-page tier bits travel, so a mixed-tier chain
    re-materializes (quantized pages re-compact) exactly on import.
    The default engine export (page-axis tree_map) cannot carry the
    1-D tier leaf — this is the factory override it looks for."""
    idx = jnp.asarray(list(page_ids))
    (kf, kq, ks), (vf, vq, vs), tier = pools

    def sl(a):
        return a[:, :, idx]

    return ((sl(kf), sl(kq), sl(ks)), (sl(vf), sl(vq), sl(vs)),
            tier[idx])


def import_quant_pages(pools, page_ids, data):
    """Scatter an exported mixed-tier chain into a PRESSURE pool at
    ``page_ids`` (the importer's freshly allocated pages)."""
    idx = jnp.asarray(list(page_ids))
    (kf, kq, ks), (vf, vq, vs), tier = pools
    (kfd, kqd, ksd), (vfd, vqd, vsd), td = data

    def st(a, d):
        return a.at[:, :, idx].set(d)

    return ((st(kf, kfd), st(kq, kqd), st(ks, ksd)),
            (st(vf, vfd), st(vq, vqd), st(vs, vsd)),
            tier.at[idx].set(td))


# --- heterogeneous-handoff transforms (reshard-on-import) -------------------

def repage_kv_data(data, page_size_from: int, page_size_to: int,
                   n_tokens: int):
    """Re-page an exported KV chain across page geometries: every leaf
    is ``(L, Hkv, n_pages, page_size, *tail)`` (page content ``tail =
    (hd,)``; the int8 scale leaves' ``tail = ()``), tokens packed
    contiguously in chain order — so the transform is flatten the slot
    axis, keep the ``n_tokens`` real positions, pad to the destination
    chain's slot count, refold. Pad slots sit beyond the row's length
    like the slack of a directly-prefilled last page: data slots pad 0,
    per-slot scale leaves pad 1 (the pool-init scale, so the adopted
    chain is indistinguishable from one written in place). PRESSURE
    chains never reach here — their per-page tier bits have no
    token-resolution meaning, so ``handoff_steps`` refuses the pairing
    upstream."""
    n_to = -(-n_tokens // page_size_to)

    def one(a):
        a = np.asarray(a)
        L, H, n, ps = a.shape[:4]
        tail = a.shape[4:]
        if n * ps < n_tokens:
            raise ValueError(
                f"repage: chain carries {n}x{ps} slots but claims "
                f"{n_tokens} tokens")
        flat = a.reshape(L, H, n * ps, *tail)[:, :, :n_tokens]
        pad = n_to * page_size_to - n_tokens
        if pad:
            fill = np.ones if len(tail) == 0 else np.zeros
            flat = np.concatenate(
                [flat, fill((L, H, pad) + tail, a.dtype)], axis=2)
        return flat.reshape(L, H, n_to, page_size_to, *tail)

    return jax.tree_util.tree_map(one, data)


def transcode_kv_data(data, quant_from, quant_to):
    """Transcode an exported FULL-PRECISION chain ``(k, v)`` into the
    destination codec. Runs the SAME ``_q8`` per-slot absmax codec the
    destination's own write path uses (``_cache_write``), so a
    transcoded page is bit-identical to the page a direct int8 engine
    would have written from the same K/V values.

    - ``'int8'``: ``((k_int8, k_scale), (v_int8, v_scale))`` — scales
      stamped per slot over head_dim, the int8 pool leaf structure.
    - ``'pressure'``: both arenas plus an ALL-SET tier mask — the
      imported chain lands parked in the int8 tier (that is what the
      priced transcode bought; the caller mirrors the positions into
      ``quant_pages`` so the importer's byte census prices it), and the
      fp arena keeps the exact source values so a later rewrite/tier
      clear reads them back.

    Quantized sources do not transcode: int8 cannot recover precision
    (→ fp refused) and carries no tier bits (→ pressure refused);
    ``handoff_steps`` refuses those pairings before data ever moves."""
    if quant_from is not None:
        raise ValueError(
            f"transcode: source codec {quant_from!r} is not "
            "transcodable (only full-precision chains re-encode)")
    k, v = data
    k, v = jnp.asarray(k), jnp.asarray(v)
    if quant_to == "int8":
        return _q8(k), _q8(v)
    if quant_to == "pressure":
        kq, ks = _q8(k)
        vq, vs = _q8(v)
        tier = jnp.ones((k.shape[2],), bool)
        return (k, kq, ks), (v, vq, vs), tier
    raise ValueError(f"transcode: unknown destination codec "
                     f"{quant_to!r}")


def shard_decode_params(outer, layers, tp: TPConfig):
    """Place decode weights on the TP mesh ONCE at load: layer
    projections per ``tp_layer_specs``, outer params (embeddings,
    final norm, lm head) replicated. Returns (outer, layers, mesh)."""
    mesh = tp.build_mesh()
    layers = device_put_sharded(layers, mesh, tp_layer_specs(tp.axis))
    outer = device_put_sharded(outer, mesh)
    return outer, layers, mesh


def _proj_qkv(cfg: LlamaConfig, p, h, pos, lora=None):
    """h: (B, T, H); pos: (T,) absolute positions. Returns q,k,v with
    rotary applied — q (B, nh, T, hd), k/v (B, nkv, T, hd).

    ``lora`` (multi-adapter serving only): ``(bank_l, ids, scale)`` —
    this layer's adapter-bank slice (``q_A``/``q_B``/``v_A``/``v_B``,
    each (n_slots, ...)) plus per-row slot indices; the low-rank
    ``_bgmv`` delta lands on q and v BEFORE the head reshape/rotary.
    Slot 0 holds zeros, so identity rows add an exact float 0."""
    B, T, H = h.shape
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = H // nh
    q = _mm(h, p["self_attn.q_proj.weight"])
    k = _mm(h, p["self_attn.k_proj.weight"]).reshape(B, T, nkv, hd)
    v = _mm(h, p["self_attn.v_proj.weight"])
    if lora is not None:
        bank_l, ids, scale = lora
        q = q + _bgmv(h, bank_l["q_A"], bank_l["q_B"], ids) \
            * jnp.asarray(scale, q.dtype)
        v = v + _bgmv(h, bank_l["v_A"], bank_l["v_B"], ids) \
            * jnp.asarray(scale, v.dtype)
    q = q.reshape(B, T, nh, hd)
    v = v.reshape(B, T, nkv, hd)
    q = apply_rotary(q, pos, cfg.rope_theta)
    k = apply_rotary(k, pos, cfg.rope_theta)
    return (jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2))


def _q8(x):
    """Per-(batch, head, slot) absmax int8 quantization over head_dim —
    the KV-cache codec (serving memory halves vs bf16; the dequant
    multiply fuses into the attention matmuls)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), -1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _cache_write(cache, kv, write_at):
    """Write a (B, nkv, T, hd) block at slot ``write_at``; quantized
    caches are (int8 data, f32 scales) tuples."""
    if isinstance(cache, tuple):
        data, sc = cache
        qv, s = _q8(kv)
        data = jax.lax.dynamic_update_slice(data, qv, (0, 0, write_at, 0))
        sc = jax.lax.dynamic_update_slice(sc, s, (0, 0, write_at))
        return (data, sc)
    return jax.lax.dynamic_update_slice(cache, kv, (0, 0, write_at, 0))


def _cache_read(cache, dtype):
    if isinstance(cache, tuple):
        data, sc = cache
        # dequant in f32: casting the scales to bf16 first would stack a
        # second quantization on top of the int8 rounding
        return (data.astype(jnp.float32) * sc[..., None]).astype(dtype)
    return cache


def _attend(cfg, q, k_all, v_all, key_mask):
    """q: (B, nh, T, hd); k/v_all: (B, nkv, S, hd); key_mask (T, S) or
    broadcastable bool."""
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    if nh != nkv:
        k_all = jnp.repeat(k_all, nh // nkv, axis=1)
        v_all = jnp.repeat(v_all, nh // nkv, axis=1)
    hd = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_all) / math.sqrt(hd)
    s = jnp.where(key_mask, s, jnp.finfo(s.dtype).min)
    probs = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v_all)


def _layer_math(cfg, lp, x, pos_vec, attend, lora=None):
    """The shared decoder-layer body (rms -> qkv+rope -> attend ->
    o_proj residual -> mlp residual); ``attend(q, k, v) -> (ctx, extra)``
    owns the cache strategy so the two cache variants below can't
    diverge on the math. ``lora`` is the optional per-layer
    multi-adapter delta (see ``_proj_qkv``)."""
    B, T, H = x.shape
    h = _rms(x, lp["input_layernorm.weight"], cfg.rms_norm_eps)
    q, k, v = _proj_qkv(cfg, lp, h, pos_vec, lora=lora)
    ctx, extra = attend(q, k, v)
    attn = _mm(jnp.swapaxes(ctx, 1, 2).reshape(B, T, H),
               lp["self_attn.o_proj.weight"])
    x = x + attn
    h2 = _rms(x, lp["post_attention_layernorm.weight"], cfg.rms_norm_eps)
    mlp = _mm(jax.nn.silu(_mm(h2, lp["mlp.gate_proj.weight"]))
              * _mm(h2, lp["mlp.up_proj.weight"]),
              lp["mlp.down_proj.weight"])
    return x + mlp, extra


def _layer_step(cfg, lp, x, k_cache, v_cache, pos_vec, key_mask, write_at):
    """One decoder layer over T positions with cache read+write.

    x: (B, T, H); caches (B, nkv, max_len, hd); pos_vec (T,) absolute
    positions; write_at: scalar start index where this block's K/V land.
    Returns (x_out, new_k_cache, new_v_cache).
    """
    def attend(q, k, v):
        kc = _cache_write(k_cache, k, write_at)
        vc = _cache_write(v_cache, v, write_at)
        k_all = _cache_read(kc, q.dtype)
        v_all = _cache_read(vc, q.dtype)
        if isinstance(kc, tuple):
            # overlay the EXACT current block over the dequantized cache:
            # this step's own keys aren't round-tripped (quantization
            # error applies only to the stored past, matching the rolling
            # prefill path)
            k_all = jax.lax.dynamic_update_slice(
                k_all, k.astype(k_all.dtype), (0, 0, write_at, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, v.astype(v_all.dtype), (0, 0, write_at, 0))
        return _attend(cfg, q, k_all, v_all, key_mask), (kc, vc)

    x, (kc, vc) = _layer_math(cfg, lp, x, pos_vec, attend)
    return x, kc, vc


def _logits(cfg, outer, x_last):
    head = outer.get("lm_head.weight")
    if head is None:
        # tied embeddings stay unquantized (the same array feeds the
        # token lookup, where int8 would distort every embedding)
        return x_last @ outer["model.embed_tokens.weight"].T
    return _mm(x_last, head)


def _layer_step_rolling_prefill(cfg, lp, x, pos_vec, key_mask, W,
                                quantized=False):
    """Prefill layer for a ROLLING (sliding-window) cache: attention runs
    banded over this block's own K/V, then only the last W positions land
    in the cache, each at slot p % W (~ Mistral's rolling buffer — cache
    memory is O(window), not O(sequence))."""
    B, S0, _ = x.shape

    def attend(q, k, v):
        ctx = _attend(cfg, q, k, v, key_mask)
        if S0 >= W:
            # slot for absolute position p is p % W; the last W positions
            # in order are a cyclic shift of the slot sequence
            kc = jnp.roll(k[:, :, S0 - W:, :], S0 % W, axis=2)
            vc = jnp.roll(v[:, :, S0 - W:, :], S0 % W, axis=2)
        else:
            nkv, hd = k.shape[1], k.shape[-1]
            kc = jnp.zeros((B, nkv, W, hd), k.dtype).at[:, :, :S0].set(k)
            vc = jnp.zeros((B, nkv, W, hd), v.dtype).at[:, :, :S0].set(v)
        if quantized:
            kc, vc = _q8(kc), _q8(vc)
        return ctx, (kc, vc)

    x, (kc, vc) = _layer_math(cfg, lp, x, pos_vec, attend)
    return x, kc, vc


def llama_decode_factory(model: LlamaForCausalLM, max_len: int = 256,
                         kv_cache_dtype: str | None = None,
                         weight_dtype: str | None = None,
                         scan_layers: bool = True):
    """Returns ``generate(tokens, max_new_tokens, key=None,
    temperature=0.0, top_k=0) -> (B, S0+max_new) token array`` running a
    fully jitted prefill + per-token decode with functional KV caches.

    ``scan_layers`` (default True) runs the stacked (L, ...) layer
    weights through ONE ``lax.scan`` layer body; False unrolls the L
    layers into the program (parity/debug fallback — ~L x the HLO,
    identical tokens).

    With ``config.sliding_window`` < max_len the cache is a ROLLING
    buffer of window slots (write at pos % window): memory stays
    O(window) and generation length is unbounded by the cache.

    ``kv_cache_dtype="int8"`` stores the cache quantized (per-slot absmax
    over head_dim): cache memory halves vs bf16 and the dequant fuses
    into the attention matmuls — the serving-memory lever the
    reference's fused_multi_transformer lacks.

    ``weight_dtype="int8"`` additionally quantizes the projection and
    lm-head weights per output channel (~ QuantizationFreezePass +
    fused int8 inference, paddle/fluid/operators/fused/): activations
    quantize dynamically per tensor and the matmuls run int8 x int8 ->
    int32 on the MXU — half the weight HBM traffic, which is what bounds
    small-batch decode. Tied embeddings stay full precision.
    """
    cfg = model.config
    outer, layers = split_params(model)
    if weight_dtype not in (None, "int8"):
        raise ValueError(f"weight_dtype {weight_dtype!r}: use None or "
                         "'int8'")
    if weight_dtype == "int8":
        layers = _quantize_weights(layers, _PROJ_KEYS)
        outer = _quantize_weights(outer, ("lm_head.weight",))
    L = cfg.num_hidden_layers
    nkv = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    window = getattr(cfg, "sliding_window", None)
    rolling = window is not None and window < max_len
    C = window if rolling else max_len  # cache slots
    quantized = kv_cache_dtype == "int8"
    if kv_cache_dtype not in (None, "int8"):
        raise ValueError(f"kv_cache_dtype {kv_cache_dtype!r}: use None "
                         "(model dtype) or 'int8'")

    def init_caches(B, dtype):
        if quantized:
            return (jnp.zeros((L, B, nkv, C, hd), jnp.int8),
                    jnp.ones((L, B, nkv, C), jnp.float32))
        return jnp.zeros((L, B, nkv, C, hd), dtype)

    def _band(S0):
        causal = jnp.tril(jnp.ones((S0, S0), bool))
        if window is not None:
            i = jnp.arange(S0)[:, None]
            j = jnp.arange(S0)[None, :]
            causal &= (i - j) < window
        return causal

    if rolling:
        # rolling prefill PRODUCES the caches (scan ys) — no zero-filled
        # buffers allocated and threaded through as dead inputs
        @jax.jit
        def prefill(outer, layers, tokens):
            B, S0 = tokens.shape
            x = jnp.take(outer["model.embed_tokens.weight"], tokens,
                         axis=0)
            pos_vec = jnp.arange(S0)
            band_mask = _band(S0)  # vs this block's own S0 keys

            def body(x, lp):
                x, kc, vc = _layer_step_rolling_prefill(
                    cfg, lp, x, pos_vec, band_mask, C, quantized)
                return x, (kc, vc)

            x, (k_caches, v_caches) = _stack_apply(body, x, layers,
                                                   scan_layers)
            x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
            return _logits(cfg, outer, x[:, -1]), k_caches, v_caches
    else:
        @partial(jax.jit, donate_argnums=(3, 4))
        def prefill(outer, layers, tokens, k_caches, v_caches):
            B, S0 = tokens.shape
            x = jnp.take(outer["model.embed_tokens.weight"], tokens,
                         axis=0)
            pos_vec = jnp.arange(S0)
            key_mask = jnp.concatenate(
                [_band(S0), jnp.zeros((S0, max_len - S0), bool)], axis=1)

            def body(x, per_layer):
                lp, kc, vc = per_layer
                x, kc, vc = _layer_step(cfg, lp, x, kc, vc, pos_vec,
                                        key_mask, 0)
                return x, (kc, vc)

            x, (k_caches, v_caches) = _stack_apply(
                body, x, (layers, k_caches, v_caches), scan_layers)
            x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
            return _logits(cfg, outer, x[:, -1]), k_caches, v_caches

    # donate the caches: dynamic_update_slice aliases in place instead of
    # copying the whole (L,B,nkv,C,hd) buffers every token
    @partial(jax.jit, donate_argnums=(4, 5))
    def decode_step(outer, layers, token, pos, k_caches, v_caches):
        """token: (B,) int; pos: scalar absolute position of `token`."""
        x = jnp.take(outer["model.embed_tokens.weight"], token[:, None],
                     axis=0)
        pos_vec = jnp.full((1,), pos)
        if rolling:
            # every cache slot already written is within the window by
            # construction (the buffer only ever holds the last C keys)
            key_mask = ((jnp.arange(C) <= pos) | (pos >= C))[None, :]
            write_at = jax.lax.rem(pos, C)
        else:
            key_mask = (jnp.arange(C) <= pos)[None, :]
            write_at = pos

        def body(x, per_layer):
            lp, kc, vc = per_layer
            x, kc, vc = _layer_step(cfg, lp, x, kc, vc, pos_vec,
                                    key_mask, write_at)
            return x, (kc, vc)

        x, (k_caches, v_caches) = _stack_apply(
            body, x, (layers, k_caches, v_caches), scan_layers)
        x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
        return _logits(cfg, outer, x[:, 0]), k_caches, v_caches

    def sample(logits, key, temperature, top_k, top_p):
        if temperature <= 0.0:
            return jnp.argmax(logits, -1)
        logits = logits / temperature
        top_k = min(top_k, logits.shape[-1])  # huge k = no truncation
        if top_k > 0:
            kth = jnp.sort(logits, -1)[:, -top_k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p < 1.0:
            # nucleus: keep the smallest prefix of the sorted
            # distribution whose mass reaches top_p; top_p <= 0 clamps to
            # the minimal nucleus (top-1, i.e. greedy) so the parameter
            # stays monotonic instead of 0.0 meaning "unrestricted"
            p = max(float(top_p), 1e-9)
            srt = jnp.sort(logits, -1)[:, ::-1]
            probs = jax.nn.softmax(srt, -1)
            cum = jnp.cumsum(probs, -1)
            keep = (cum - probs) < p  # mass BEFORE this token
            cutoff = jnp.where(keep, srt, jnp.inf).min(-1, keepdims=True)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(key, logits, -1)

    def generate(tokens, max_new_tokens: int, key=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, eos_token_id: int | None = None,
                 pad_token_id: int = 0):
        """``eos_token_id`` enables batched early stop: rows that have
        emitted EOS produce ``pad_token_id`` from then on, and the decode
        loop exits once every row has finished."""
        tokens = jnp.asarray(tokens)
        B, S0 = tokens.shape
        if not rolling and S0 + max_new_tokens > max_len:
            # hard error (not assert): past max_len the cache writes
            # would silently clamp and corrupt generations (the rolling
            # window cache has no such limit — it wraps by design)
            raise ValueError(
                f"prompt {S0} + max_new_tokens {max_new_tokens} exceeds "
                f"the factory's max_len {max_len}")
        if key is None:
            key = jax.random.PRNGKey(0)
        dtype = outer["model.embed_tokens.weight"].dtype
        if rolling:
            logits, kc, vc = prefill(outer, layers, tokens)
        else:
            kc = init_caches(B, dtype)
            vc = init_caches(B, dtype)
            logits, kc, vc = prefill(outer, layers, tokens, kc, vc)
        out = [tokens]
        pos = S0
        finished = jnp.zeros((B,), bool)
        for i in range(max_new_tokens):
            key, sub = jax.random.split(key)
            nxt = sample(logits, sub, temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(finished, pad_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            out.append(nxt[:, None])
            # all-finished poll every 8 steps: the bool() readback is a
            # host sync that would otherwise serialize the async decode
            # dispatch pipeline on EVERY token (costly over the tunnel);
            # at most 7 wasted padded steps in exchange
            if eos_token_id is not None \
                    and (i % 8 == 7 or i + 1 == max_new_tokens) \
                    and bool(finished.all()):
                break  # every row has emitted EOS
            if i + 1 < max_new_tokens:
                logits, kc, vc = decode_step(outer, layers, nxt,
                                             jnp.asarray(pos), kc, vc)
                pos += 1
        return jnp.concatenate(out, axis=1)

    @partial(jax.jit, static_argnums=(3,))
    def _compiled_greedy(outer, layers, tokens, max_new):
        """prefill + max_new greedy decode steps in ONE program
        (lax.scan): generate()'s python loop pays a per-token host
        dispatch, which through a remote-PJRT tunnel (~7 ms/call)
        dominates small-batch decode; the in-jit loop has one dispatch
        per CALL (round-5 discovery via the speculative while_loop —
        spec beat 'plain' 4x at 0% acceptance purely on dispatch)."""
        B, S0 = tokens.shape
        dtype = outer["model.embed_tokens.weight"].dtype
        if rolling:
            logits, kc, vc = prefill(outer, layers, tokens)
        else:
            kc = init_caches(B, dtype)
            vc = init_caches(B, dtype)
            logits, kc, vc = prefill(outer, layers, tokens, kc, vc)

        def step(carry, i):
            logits, kc, vc = carry
            nxt = jnp.argmax(logits, -1)
            logits, kc, vc = decode_step(outer, layers, nxt, S0 + i,
                                         kc, vc)
            return (logits, kc, vc), nxt

        (logits, _, _), toks = jax.lax.scan(
            step, (logits, kc, vc), jnp.arange(max_new - 1))
        last = jnp.argmax(logits, -1)
        gen = jnp.concatenate([jnp.swapaxes(toks, 0, 1),
                               last[:, None]], 1) if max_new > 1 \
            else last[:, None]
        return jnp.concatenate([tokens, gen], axis=1)

    def generate_compiled(tokens, max_new_tokens: int):
        """Greedy-only one-program variant of generate() (same output
        as temperature=0)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        B, S0 = tokens.shape
        if max_new_tokens < 1:
            # match generate(): zero budget returns the prompt alone
            return np.asarray(tokens)
        if not rolling and S0 + max_new_tokens > max_len:
            raise ValueError(
                f"prompt {S0} + max_new_tokens {max_new_tokens} exceeds "
                f"the factory's max_len {max_len}")
        return np.asarray(_compiled_greedy(outer, layers, tokens,
                                           max_new_tokens))

    generate.compiled = generate_compiled
    # program introspection hooks: lower/compile the per-token step or
    # the whole greedy program without running it (program-size parity
    # tests + compile-time rows in tools/spec_decode_bench.py)
    generate._parts = {"outer": outer, "layers": layers,
                       "prefill": prefill, "decode_step": decode_step,
                       "init_caches": init_caches,
                       "compiled_greedy": _compiled_greedy,
                       "scan_layers": scan_layers, "rolling": rolling}
    return generate


def llama_speculative_decode_factory(target: LlamaForCausalLM,
                                     draft: LlamaForCausalLM,
                                     max_len: int = 256,
                                     n_draft: int = 4,
                                     scan_layers: bool = True):
    """Greedy speculative decoding: a small draft model proposes
    ``n_draft`` tokens (ONE jitted program — the autoregressive draft
    walk runs as an in-jit scan, so the whole draft phase costs a single
    host readback); the target model VERIFIES them in ONE batched block
    step (k+1 positions through the cache — matmul-heavy, instead of k+1
    sequential target steps). Accepted-prefix + the target's correction
    token advance the sequence; rejected cache slots are overwritten by
    the next block (the key mask never reaches stale slots beyond the
    write position), so rollback is free. On a fully-accepted round the
    draft hasn't consumed its own last proposal — it is fed as part of
    the next round's block, so the draft cache never holds a hole.

    Greedy acceptance makes the output EXACTLY the target model's greedy
    generation — speculation changes latency, never content. The serving
    analog the reference's fused_multi_transformer stack lacks.

    Both models must share a vocabulary. Batch size 1 per call (the
    accepted-prefix length is data-dependent; batching rows with
    different acceptance lengths needs per-row position bookkeeping —
    future work).

    ``scan_layers`` (default True) runs BOTH models' stacked (L, ...)
    layer weights through one ``lax.scan`` layer body per block — the
    two-model program is the largest HLO in the repo and scan-compression
    is what lets it compile at 0.44B; False unrolls the layers
    (parity/debug fallback, ~L x the program)."""
    if target.config.vocab_size != draft.config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    if n_draft < 1:
        raise ValueError("n_draft must be >= 1 (0 would still emit one "
                         "unverified draft per round and desync the draft "
                         "cache)")
    if getattr(target.config, "sliding_window", None) or \
            getattr(draft.config, "sliding_window", None):
        raise ValueError("speculative decoding with sliding_window is "
                         "not supported (rolling slots break the "
                         "overwrite-rollback invariant)")

    def build(model):
        cfg = model.config
        outer, layers = split_params(model)
        L = cfg.num_hidden_layers
        nkv = cfg.num_key_value_heads
        hd = cfg.hidden_size // cfg.num_attention_heads
        dtype = outer["model.embed_tokens.weight"].dtype

        def init(B):
            return (jnp.zeros((L, B, nkv, max_len, hd), dtype),
                    jnp.zeros((L, B, nkv, max_len, hd), dtype))

        def block_body(outer, layers, tokens, k_caches, v_caches, pos0):
            """tokens (B, T) at absolute positions pos0..pos0+T-1; writes
            their K/V at the same slots; returns logits for EVERY
            position (B, T, V)."""
            T = tokens.shape[1]
            x = jnp.take(outer["model.embed_tokens.weight"], tokens,
                         axis=0)
            pos_vec = pos0 + jnp.arange(T)
            key_mask = jnp.arange(max_len)[None, :] <= pos_vec[:, None]

            def body(x, per_layer):
                lp, kc, vc = per_layer
                x, kc, vc = _layer_step(cfg, lp, x, kc, vc, pos_vec,
                                        key_mask, pos0)
                return x, (kc, vc)

            x, (k_caches, v_caches) = _stack_apply(
                body, x, (layers, k_caches, v_caches), scan_layers)
            x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
            return _logits(cfg, outer, x), k_caches, v_caches

        block = partial(jax.jit, donate_argnums=(3, 4))(block_body)
        return outer, layers, init, block_body, block

    outerT, layersT, initT, blockT_body, blockT = build(target)
    outerD, layersD, initD, blockD_body, _ = build(draft)

    @partial(jax.jit, donate_argnums=(3, 4), static_argnums=(5,))
    def draft_round(outer, layers, feed, k_caches, v_caches, k, pos0):
        """Consume the pending ``feed`` block (ends at position pos0 +
        T0 - 1), then greedily draft ``k`` tokens with an in-jit scan —
        the whole draft phase is one program, one readback."""
        T0 = feed.shape[1]
        lg, k_caches, v_caches = blockD_body(outer, layers, feed,
                                             k_caches, v_caches, pos0)
        cur = jnp.argmax(lg[:, -1], -1)  # (B,) — the first draft token

        def step(carry, i):
            cur, kc, vc = carry
            lg, kc, vc = blockD_body(outer, layers, cur[:, None], kc, vc,
                                     pos0 + T0 + i)
            return (jnp.argmax(lg[:, -1], -1), kc, vc), cur

        (last_d, k_caches, v_caches), ds = jax.lax.scan(
            step, (cur, k_caches, v_caches), jnp.arange(k - 1))
        # ds: (k-1, B) of d_0..d_{k-2}; last carry is d_{k-1}
        drafts = jnp.concatenate(
            [jnp.swapaxes(ds, 0, 1), last_d[:, None]], 1) \
            if k > 1 else last_d[:, None]
        return drafts, k_caches, v_caches

    # Both models' weights travel as ARGUMENTS through every jitted
    # spec program, never as closure captures: a closed-over array is
    # embedded in the lowered module as a literal constant, so the
    # two-model program used to carry ~2 model-sizes of inline weight
    # bytes — THE reason the remote compile service hung then broke its
    # pipe at 0.44B while the plain decode (weights as args, ~kB of
    # HLO) compiled in 1.6 s. With args + the scanned layer body the
    # spec module text is size-O(1) in both depth and width.
    _params = (outerT, layersT, outerD, layersD)

    @jax.jit
    def _spec_prefill(params, tokens):
        """Prefill both models; returns the spec loop state."""
        pouterT, playersT, pouterD, playersD = params
        B, S0 = tokens.shape
        kT, vT = initT(B)
        kD, vD = initD(B)
        lgT, kT, vT = blockT_body(pouterT, playersT, tokens, kT, vT, 0)
        last = jnp.argmax(lgT[0, -1], -1).astype(jnp.int32)
        seq = jnp.zeros((max_len,), jnp.int32)
        seq = jax.lax.dynamic_update_slice(seq, tokens[0].astype(
            jnp.int32), (0,))
        seq = seq.at[S0].set(last)
        _, kD, vD = blockD_body(pouterD, playersD, tokens, kD, vD, 0)
        return (jnp.asarray(1, jnp.int32), jnp.asarray(0, jnp.int32),
                jnp.asarray(S0, jnp.int32), last, seq, kT, vT, kD, vD)

    def _spec_round(params, state):
        """One draft/verify/accept round. Greedy acceptance arithmetic
        is branch-free: n = length of the matching draft prefix; the
        candidate vector writes accepted drafts then the target's
        correction; junk beyond n is overwritten by later rounds (the
        same overwrite-rollback invariant the caches use)."""
        pouterT, playersT, pouterD, playersD = params
        produced, rounds, pos, last, seq, kT, vT, kD, vD = state
        k = n_draft
        feed = jax.lax.dynamic_slice(seq, (pos - 1,), (2,))[None]
        lg, kD2, vD2 = blockD_body(pouterD, playersD, feed, kD, vD,
                                   pos - 1)
        cur = jnp.argmax(lg[:, -1], -1)

        # inner draft walk as a scan: one traced draft block instead of
        # k-1 unrolled copies — program size is what breaks the axon
        # remote compiler, and scan-in-scan compiles fine (the unrolled
        # form did not at real model sizes)
        def dstep(carry, i):
            cur, kc, vc = carry
            lg, kc, vc = blockD_body(pouterD, playersD, cur[:, None],
                                     kc, vc, pos + 1 + i)
            return (jnp.argmax(lg[:, -1], -1), kc, vc), cur

        (last_d, kD2, vD2), ds = jax.lax.scan(
            dstep, (cur, kD2, vD2), jnp.arange(k - 1))
        drafts = (jnp.concatenate([jnp.swapaxes(ds, 0, 1),
                                   last_d[:, None]], 1)
                  if k > 1 else last_d[:, None])  # (1, k)
        blk = jnp.concatenate([last[None], drafts[0]])[None]
        lgT, kT2, vT2 = blockT_body(pouterT, playersT,
                                    blk.astype(jnp.int32), kT, vT, pos)
        t = jnp.argmax(lgT[0], -1).astype(jnp.int32)  # (k+1,)
        matches = (drafts[0].astype(jnp.int32) == t[:k]).astype(
            jnp.int32)
        n = jnp.sum(jnp.cumprod(matches))
        idx = jnp.arange(k + 1)
        dpad = jnp.concatenate([drafts[0].astype(jnp.int32),
                                jnp.zeros((1,), jnp.int32)])
        cand = jnp.where(idx < n, dpad, t)
        seq = jax.lax.dynamic_update_slice(seq, cand, (pos + 1,))
        last = jax.lax.dynamic_index_in_dim(t, n, keepdims=False)
        return (produced + n + 1, rounds + 1, pos + n + 1, last,
                seq, kT2, vT2, kD2, vD2)

    @partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
    def _spec_chunk(params, state, R, max_new):
        """R gated rounds inside ONE lax.scan program. The original
        while_loop formulation is semantically identical but the axon
        tunnel's remote compiler hangs >35 min on While programs at
        real model sizes while this scan compiles in seconds (the same
        discovery as the compiled plain decode). Rounds past max_new
        become no-ops: the fresh state is computed then discarded by a
        scalar select, so output and stats are EXACTLY the while_loop's.
        The host re-dispatches chunks until produced >= max_new — ONE
        dispatch when acceptance is high (R is sized for the accepted
        case), <= k+1 when the draft never matches."""
        def body(state, _):
            new_state = _spec_round(params, state)
            valid = state[0] < max_new
            state = jax.tree_util.tree_map(
                lambda a, b: jnp.where(valid, b, a), state, new_state)
            return state, None

        state, _ = jax.lax.scan(body, state, None, length=R)
        return state

    def _compiled_spec(tokens, max_new):
        state = _spec_prefill(_params, tokens)
        # chunk size caps the compiled program (the axon remote compiler
        # broke its pipe on large programs); at high acceptance 128
        # tokens costs ~7 dispatches at R=4 (vs 2 per ROUND for the
        # python loop)
        # R static (scan length, few values); max_new TRACED (only the
        # gating comparison reads it) so one compile serves every
        # generation length; state donated so the KV caches alias
        # across chunk re-dispatches instead of copying
        R = min(4, max(1, -(-max_new // (n_draft + 1))))
        mn = jnp.asarray(max_new, jnp.int32)
        while int(state[0]) < max_new:
            state = _spec_chunk(_params, state, R, mn)
        return state[4], state[0], state[1]

    def generate_compiled(tokens, max_new_tokens: int):
        """One-program speculative decode; same greedy-exact output as
        ``generate`` (stats in .last_stats after each call)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        B, S0 = tokens.shape
        if B != 1:
            raise ValueError("speculative generate supports batch 1")
        if S0 + max_new_tokens + 2 * (n_draft + 1) > max_len:
            raise ValueError(
                f"prompt {S0} + max_new {max_new_tokens} + 2x draft "
                f"window {n_draft + 1} exceeds max_len {max_len}")
        seq, produced, rounds = _compiled_spec(tokens, max_new_tokens)
        seq = np.asarray(seq)
        produced, rounds = int(produced), int(rounds)
        # produced = 1 (prefill token) + sum(n_i + 1): subtract the
        # prefill token AND the per-round correction token so the rate
        # counts only accepted DRAFT proposals
        generate_compiled.last_stats = {
            "rounds": rounds,
            "tokens": min(produced, max_new_tokens),
            "target_steps": 1 + rounds,
            "accept_rate": round(
                (produced - 1 - rounds) / max(1, rounds * n_draft), 4),
        }
        return seq[None, :S0 + max_new_tokens]

    generate_compiled.last_stats = {}
    # PR-4 convention: a python shim driving jitted programs
    # advertises them via _jit_inner, so program-cache-growth
    # detection (engine jit.compile instants, cache_stats consumers)
    # sees spec compiles instead of missing them behind the shim
    generate_compiled._jit_inner = (_spec_prefill, _spec_chunk)

    def generate(tokens, max_new_tokens: int):
        tokens = jnp.asarray(tokens)
        B, S0 = tokens.shape
        if B != 1:
            raise ValueError("speculative generate supports batch 1")
        if S0 + max_new_tokens + n_draft + 1 > max_len:
            raise ValueError(
                f"prompt {S0} + max_new {max_new_tokens} + draft window "
                f"{n_draft + 1} exceeds max_len {max_len}")
        kT, vT = initT(B)
        kD, vD = initD(B)
        logitsT, kT, vT = blockT(outerT, layersT, tokens, kT, vT, 0)
        seq = [int(t) for t in np.asarray(tokens)[0]]
        last = int(np.asarray(jnp.argmax(logitsT[:, -1], -1))[0])
        seq.append(last)
        produced = 1
        pos = S0          # `last` occupies sequence position pos
        pending = seq[S0:]  # tokens the DRAFT has not consumed yet
        # (the draft skipped prefill of nothing: feed it the prompt too)
        _, kD, vD = draft_round(
            outerD, layersD, tokens, kD, vD, 1,
            jnp.asarray(0))  # consumes prompt; 1 throwaway draft token
        rounds = 0
        while produced < max_new_tokens:
            k = min(n_draft, max_new_tokens - produced)
            feed = jnp.asarray([pending], jnp.int32)
            T0 = len(pending)
            drafts_arr, kD, vD = draft_round(
                outerD, layersD, feed, kD, vD, k,
                jnp.asarray(pos - T0 + 1))
            drafts = [int(x) for x in np.asarray(drafts_arr)[0]]
            # ONE target block verifies [last, d0..d_{k-1}]
            blk = jnp.asarray([[last] + drafts], jnp.int32)
            lgT, kT, vT = blockT(outerT, layersT, blk, kT, vT,
                                 jnp.asarray(pos))
            t = [int(x) for x in np.asarray(jnp.argmax(lgT[0], -1))]
            n = 0
            while n < k and drafts[n] == t[n]:
                n += 1
            seq.extend(drafts[:n] + [t[n]])  # accepted + correction/bonus
            produced += n + 1
            pos += n + 1
            last = t[n]
            # the draft consumed [pending, d0..d_{k-2}]; feed it whatever
            # of the accepted sequence it hasn't seen, plus the new last
            pending = ([drafts[k - 1]] if n == k else []) + [last]
            rounds += 1
        out = np.asarray(seq[:S0 + max_new_tokens], np.int32)[None, :]
        generate.last_stats = {
            "rounds": rounds,
            "tokens": min(produced, max_new_tokens),
            "target_steps": 1 + rounds,
        }
        return out

    generate.last_stats = {}
    # one-program-per-chunk variant (host-redispatched lax.scan chunks;
    # the while_loop form breaks the axon remote compiler at real model
    # sizes): identical greedy output, ~max_new/(R*(k+1)) dispatches
    # instead of two per round
    generate.compiled = generate_compiled
    # lower/compile the chunk program without generating (compile-time
    # + program-size measurement at sizes where RUNNING is impractical)
    generate._parts = {"spec_prefill": _spec_prefill,
                       "spec_chunk": _spec_chunk,
                       "params": _params,
                       "scan_layers": scan_layers}
    return generate


# --- paged decode (continuous batching) ------------------------------------

def llama_paged_decode_factory(model: LlamaForCausalLM,
                               page_size: int = 64,
                               n_pool_pages: int = 256,
                               chunked_prefill: int | None = None,
                               kv_cache_dtype: str | None = None,
                               emit: str = "token",
                               prefill_attention: str = "gather",
                               scan_layers: bool = True,
                               tp: "TPConfig | int | None" = None,
                               lora: "LoRAConfig | tuple | None"
                               = None,
                               kv_quant: str | None = None):
    """Compiled decode over a PAGED KV pool — the continuous-batching
    serving path (ops/pallas/paged_attention.py; the reference's dense
    fused_multi_transformer cache cannot share memory across requests).

    Per layer the pool is (Hkv, P, page_size, hd); sequences hold page
    tables (B, pages_per_seq — the caller's table width) and real
    lengths (B,). Ragged batches are
    first-class: rotary positions, cache writes and attention masks are
    all per-sequence, so requests at different depths decode together in
    ONE jitted step — admit/evict between steps by editing the tables
    (PagedKVCache does the host bookkeeping).

    Returns (outer, layers, pools, prefill, decode_step):
      pools: (k_pools, v_pools) each (L, Hkv, P, page_size, hd)
      prefill(outer, layers, tokens (B,T), page_tables, lengths, pools)
          -> (next_token (B,), pools')   [prompt K/V written to pages]
      decode_step(outer, layers, tok (B,), page_tables, lengths, pools)
          -> (next_token (B,), pools')   [lengths' = lengths + 1 is the
                                          caller's bookkeeping]

    ``chunked_prefill=C`` (a page multiple): the returned prefill walks
    the prompt in C-token chunks, each attending causally to the pool
    pages written so far — score memory per layer is O(C x table_width
    x page_size) instead of the one-shot O(T^2): the long-prompt
    admission path of serving stacks (vLLM's chunked prefill).

    ``kv_cache_dtype="int8"``: pool pages store the per-slot absmax
    int8 codec (the dense cache's _q8) — serving cache memory halves
    and the Pallas kernel dequantizes in VMEM per page.

    ``kv_quant``: the serving-tier spelling of the pool codec.
    ``"int8"`` is always-int8 — identical storage to
    ``kv_cache_dtype="int8"``. ``"pressure"`` keeps hot pages full
    precision and adds an int8+scale shadow arena plus a (P,) page
    tier mask (all jit inputs): ``compact_kv_pages`` quantizes parked
    pages under byte pressure, reads merge both tiers through ONE
    fixed-shape where(), and the write paths clear a written page's
    tier bit in-program — so compaction churn and page recycling
    never recompile and never read stale int8 content.

    ``emit="logits"``: prefill/decode_step return the last-position
    logits (B, V) instead of greedy tokens, so the serving loop owns
    sampling (temperature/top-k/top-p live with the request, not the
    compiled program — the dense factory's in-jit sampler is the other
    option when the whole loop is compiled).

    ``prefill_attention="kernel"`` (chunked prefill only): attend each
    chunk through the paged_prefill_attention Pallas kernel instead of
    the dense page gather — no (B, nkv, S, hd) gathered temporary, and
    int8 pools stay int8 all the way into VMEM. "gather" remains the
    default until the kernel carries a chip measurement.

    ``scan_layers`` (default True): one scanned layer body over the
    stacked (L, ...) weights and (L, ...) pools; False unrolls the
    layers into the program (parity fallback).

    ``tp`` (``TPConfig`` / int degree): shard the decode path over a
    1-D named mesh — attention heads and MLP hidden dims partitioned
    column/row-parallel (``tp_layer_specs``), the KV pools split by kv
    head (``tp_pool_spec``), embeddings/norms replicated. Placement
    happens ONCE here (NamedSharding device_put); the jitted
    prefill/decode programs are byte-for-byte the same trace — they
    inherit the arg shardings, GSPMD inserts the collectives, and the
    fixed-shape ``decode_n`` batches still never recompile across
    churn. ``tp=None`` builds exactly the single-device factory.

    ``lora`` (``LoRAConfig`` / ``(n_slots, rank)``): multi-adapter
    serving. Every prefill/decode callable accepts a trailing
    ``lora=(adapter_bank, adapter_ids)`` argument — the bank is the
    device-resident stack of per-slot low-rank q/v deltas
    (``lora_bank_hooks`` builds and uploads it), ``adapter_ids`` the
    per-row slot indices — applied per row via the batched ``_bgmv``
    gather. Both are jit inputs (the PR-1 weights-as-args invariant),
    so one compiled fixed-shape program serves ANY adapter mix and
    adapter churn never recompiles. Slot 0 is the all-zero identity;
    with ``lora=None`` at the call the programs trace exactly the
    base-model math. Under ``tp`` the bank stays replicated (rank is
    tiny; the delta add reshards into the column-parallel q/v
    layout).
    """
    from ...ops.pallas.paged_attention import paged_attention

    cfg = model.config
    lora_cfg = as_lora_config(lora)
    lora_scale = lora_cfg.scale if lora_cfg is not None else 1.0
    outer, layers = split_params(model)
    outer = {k: jnp.asarray(v) for k, v in outer.items()}
    layers = {k: jnp.asarray(v) for k, v in layers.items()}
    tp = as_tp_config(tp)
    tp_mesh = None
    if tp is not None:
        _validate_tp(cfg, tp)
        outer, layers, tp_mesh = shard_decode_params(outer, layers, tp)
    L = cfg.num_hidden_layers
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = cfg.hidden_size // nh
    dtype = layers["self_attn.q_proj.weight"].dtype

    quantized = kv_cache_dtype == "int8"
    if kv_cache_dtype not in (None, "int8"):
        raise ValueError(f"kv_cache_dtype {kv_cache_dtype!r}: use None "
                         "(model dtype) or 'int8'")
    if kv_quant not in (None, "int8", "pressure"):
        raise ValueError(f"kv_quant {kv_quant!r}: use None, 'int8' "
                         "(every page stored int8+scale) or 'pressure' "
                         "(parked pages compacted to int8 under byte "
                         "pressure)")
    if kv_quant == "int8":
        # always-int8 IS the existing int8 pool codec, named at the
        # serving tier: one storage path, two spellings
        quantized = True
    pressure = kv_quant == "pressure"
    if pressure:
        if kv_cache_dtype is not None:
            raise ValueError("kv_quant='pressure' owns the pool codec "
                             "— drop kv_cache_dtype")
        if tp is not None:
            raise ValueError(
                "kv_quant='pressure' does not compose with tp= yet: "
                "the (P,) page-tier mask is a whole-pool jit input "
                "with no kv-head axis to shard — use kv_quant='int8' "
                "(scales shard with their kv heads per tp_pool_spec)")
    if emit not in ("token", "logits"):
        raise ValueError(f"emit {emit!r}: use 'token' or 'logits'")
    if prefill_attention not in ("gather", "kernel"):
        raise ValueError(f"prefill_attention {prefill_attention!r}: "
                         "use 'gather' or 'kernel'")

    def _emit(logits):
        return jnp.argmax(logits, -1) if emit == "token" \
            else logits.astype(jnp.float32)

    def _gmask(logits, grammar):
        """CONSTRAINED DECODING: mask each row's logits with its
        grammar state's packed allow-bitmask BEFORE the emit argmax.
        ``grammar`` is ``(mask_table, state_ids)`` — a
        ``(rows, ceil(V/32))`` uint32 bank and a (B,) int32 flat-id
        vector, BOTH jit inputs like lora's bank/ids, so one compiled
        program serves any schema mix and grammar churn never
        recompiles. Flat id 0 is the reserved all-allow row: free
        rows' where() keeps every logit, bit-for-bit the base math.
        ``grammar=None`` (the Python-level default) traces the
        identical base program — no mask op exists in it at all."""
        if grammar is None:
            return logits
        table, gids = grammar
        v = logits.shape[-1]
        rows = jnp.take(table, gids, axis=0)       # (B, words)
        word = jnp.arange(v) // 32
        bit = (jnp.arange(v) % 32).astype(jnp.uint32)
        allow = (jnp.take(rows, word, axis=1) >> bit[None, :]) \
            & jnp.uint32(1)
        return jnp.where(allow.astype(bool), logits,
                         jnp.asarray(-jnp.inf, logits.dtype))

    # ONE definition of how the optional adapter bank rides the layer
    # scan, shared by prefill / decode_step / _prefill_chunk (three
    # private copies could silently diverge the chunked-prefill path
    # from decode if the lora payload ever grows, e.g. k-proj deltas)
    def _scan_operand(layers, k_pools, v_pools, lora):
        return (layers, k_pools, v_pools) if lora is None \
            else (layers, lora[0], k_pools, v_pools)

    def _split_per_layer(per_layer, lora):
        """One scan step's operand -> (lp, kp_l, vp_l, lo) where
        ``lo`` is the per-layer lora triple for ``_layer_math`` (None
        without adapters)."""
        if lora is None:
            lp, kp_l, vp_l = per_layer
            return lp, kp_l, vp_l, None
        lp, bl, kp_l, vp_l = per_layer
        return lp, kp_l, vp_l, (bl, lora[1], lora_scale)

    def init_pools():
        shape = (L, nkv, n_pool_pages, page_size, hd)
        if pressure:
            # two-tier arena: full-precision pages PLUS an int8+scale
            # shadow and a (P,) tier mask saying which arena each page
            # reads from. All jit inputs — compaction flips tier bits,
            # never shapes, so the degradation tier cannot recompile.
            def one():
                return (jnp.zeros(shape, dtype),
                        jnp.zeros(shape, jnp.int8),
                        jnp.ones(shape[:-1], jnp.float32))
            return one(), one(), jnp.zeros((n_pool_pages,), bool)
        if quantized:
            def one():
                return (jnp.zeros(shape, jnp.int8),
                        jnp.ones(shape[:-1], jnp.float32))
            pools = one(), one()
        else:
            pools = jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
        if tp_mesh is not None:
            # page CONTENT splits by kv head; the spec's trailing dims
            # (and the int8 scale leaves' 4-D shape) stay replicated
            pools = device_put_sharded(pools, tp_mesh,
                                       tp_pool_spec(tp.axis))
        return pools

    def _tier_clear(pools, written_ids):
        """PRESSURE: the pages this program is about to write get
        fresh full-precision content, so their tier bit dies in the
        SAME program — a recycled page id can never read stale int8
        data (the device-side twin of PagedKVCache dropping a page's
        tier with its id). Rewrites of still-cached tails clear too:
        their fp slots hold identical content."""
        (kf, kq, ks), (vf, vq, vs), tier = pools
        tier = tier.at[written_ids.reshape(-1)].set(False)
        return (kf, kq, ks), (vf, vq, vs), tier

    def _tier_enter(pools):
        """PRESSURE: merge both arenas into ONE full-precision view
        (quantized pages dequantized through the tier mask) so every
        downstream read/write path is the unquantized program — one
        fixed-shape where() per pool, no second attention variant.
        Returns (k_view, v_view, merge_ctx); passthrough otherwise."""
        if not pressure:
            k_pools, v_pools = pools
            return k_pools, v_pools, None
        (kf, kq, ks), (vf, vq, vs), tier = pools
        t = tier[None, None, :, None, None]

        def merge(fp, qd, s):
            return jnp.where(
                t, (qd.astype(jnp.float32) * s[..., None]).astype(
                    fp.dtype), fp)

        return merge(kf, kq, ks), merge(vf, vq, vs), (pools, t)

    def _tier_exit(k_eff, v_eff, ctx):
        """PRESSURE: fold the written merged view back into the
        two-tier pool — quantized pages keep their (authoritative)
        int8 arena and old fp slots, everything else takes the writes.
        Passthrough otherwise."""
        if ctx is None:
            return k_eff, v_eff
        ((kf, kq, ks), (vf, vq, vs), tier), t = ctx
        return ((jnp.where(t, kf, k_eff), kq, ks),
                (jnp.where(t, vf, v_eff), vq, vs), tier)

    def _write_prompt(pool_l, kv, page_tables, T_pad):
        """kv (B, nkv, T_pad, hd) -> pages at the tables' first
        T_pad/page_size entries: the start=0 case of _write_chunk."""
        return _write_chunk(pool_l, kv, page_tables, 0, T_pad)

    def _write_token(pool_l, kv, page_tables, lengths):
        """kv (B, nkv, 1, hd) written at each sequence's current end."""
        pages = jnp.take_along_axis(
            page_tables, (lengths // page_size)[:, None], 1)[:, 0]
        offs = lengths % page_size
        if isinstance(pool_l, tuple):
            data, sc = pool_l
            qd, s = _q8(kv)                              # (B,nkv,1,hd)
            return (data.at[:, pages, offs].set(
                        jnp.transpose(qd[:, :, 0], (1, 0, 2))),
                    sc.at[:, pages, offs].set(s[:, :, 0].T))
        upd = jnp.transpose(kv[:, :, 0], (1, 0, 2))     # (nkv, B, hd)
        return pool_l.at[:, pages, offs].set(upd.astype(pool_l.dtype))

    @partial(jax.jit, donate_argnums=(5,))  # pools alias in place
    def prefill(outer, layers, tokens, page_tables, lengths, pools,
                lora=None, grammar=None):
        """Prompts padded to a page multiple; ``lengths`` are the REAL
        prompt lengths (padding K/V lands in allocated pages but is
        masked by lengths everywhere downstream). ``lora``: optional
        ``(adapter_bank, adapter_ids)`` multi-adapter deltas.
        ``grammar``: optional ``(mask_table, state_ids)`` constrained-
        decoding masks over the FIRST emitted token (each row's id is
        its automaton's start state; free rows pass 0)."""
        B, T = tokens.shape
        if pressure:
            pools = _tier_clear(pools,
                                page_tables[:, :T // page_size])
        k_pools, v_pools, _tm = _tier_enter(pools)
        if T % page_size:
            raise ValueError(f"prefill length {T} must be a multiple of "
                             f"page_size {page_size} (pad the prompt)")
        x = jnp.take(outer["model.embed_tokens.weight"], tokens, axis=0)
        pos_vec = jnp.arange(T)
        causal = jnp.tril(jnp.ones((T, T), bool))
        # padding keys never attend: key j valid iff j < len(b)
        key_ok = jnp.arange(T)[None, :] < lengths[:, None]
        mask = causal[None, None] & key_ok[:, None, None, :]

        def body(x, per_layer):
            lp, kp_l, vp_l, lo = _split_per_layer(per_layer, lora)

            def attend(q, k, v):
                kp = _write_prompt(kp_l, k, page_tables, T)
                vp = _write_prompt(vp_l, v, page_tables, T)
                return _attend(cfg, q, k, v, mask), (kp, vp)

            x, (kp, vp) = _layer_math(cfg, lp, x, pos_vec, attend,
                                      lora=lo)
            return x, (kp, vp)

        x, ys = _stack_apply(
            body, x, _scan_operand(layers, k_pools, v_pools, lora),
            scan_layers)
        k_pools, v_pools = ys
        x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
        # each sequence's last REAL position owns the next token
        x_last = jnp.take_along_axis(
            x, (lengths - 1)[:, None, None].astype(jnp.int32), 1)[:, 0]
        out = _emit(_gmask(_logits(cfg, outer, x_last), grammar))
        return out, _tier_exit(k_pools, v_pools, _tm)

    @partial(jax.jit, donate_argnums=(5,))  # no per-token pool copy
    def decode_step(outer, layers, tok, page_tables, lengths, pools,
                    lora=None, grammar=None):
        if pressure:
            pools = _tier_clear(pools, jnp.take_along_axis(
                page_tables, (lengths // page_size)[:, None], 1))
        k_pools, v_pools, _tm = _tier_enter(pools)
        x = jnp.take(outer["model.embed_tokens.weight"], tok,
                     axis=0)[:, None]                    # (B, 1, H)
        pos = lengths[:, None]                           # per-sequence

        def body(x, per_layer):
            lp, kp_l, vp_l, lo = _split_per_layer(per_layer, lora)

            def attend(q, k, v):
                kp = _write_token(kp_l, k, page_tables, lengths)
                vp = _write_token(vp_l, v, page_tables, lengths)
                if isinstance(kp, tuple):
                    ctx = paged_attention(
                        q[:, :, 0], kp[0], vp[0], page_tables,
                        lengths + 1, k_scales=kp[1], v_scales=vp[1])
                    ctx = ctx.astype(q.dtype)
                else:
                    ctx = paged_attention(q[:, :, 0], kp, vp,
                                          page_tables, lengths + 1)
                return ctx[:, :, None], (kp, vp)

            x, (kp, vp) = _layer_math(cfg, lp, x, pos, attend,
                                      lora=lo)
            return x, (kp, vp)

        x, ys = _stack_apply(
            body, x, _scan_operand(layers, k_pools, v_pools, lora),
            scan_layers)
        k_pools, v_pools = ys
        x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
        out = _emit(_gmask(_logits(cfg, outer, x[:, 0]), grammar))
        return out, _tier_exit(k_pools, v_pools, _tm)

    @partial(jax.jit, donate_argnums=(6,))
    def _prefill_chunk(outer, layers, chunk, start, page_tables, lengths,
                       pools, x_last, lora=None):
        """One C-token chunk at absolute positions start..start+C-1:
        writes its pages, attends to every pool position < start+C, and
        harvests the hidden state of each sequence's (length-1) row when
        it falls inside this chunk."""
        B, C = chunk.shape
        if pressure:
            pools = _tier_clear(pools, jax.lax.dynamic_slice_in_dim(
                page_tables, start // page_size, C // page_size, 1))
        k_pools, v_pools, _tm = _tier_enter(pools)
        W = page_tables.shape[1]
        S = W * page_size
        x = jnp.take(outer["model.embed_tokens.weight"], chunk, axis=0)
        pos_vec = start + jnp.arange(C)
        # causal over ABSOLUTE key positions, bounded by real length
        key_ok = (jnp.arange(S)[None, None, :]
                  <= (start + jnp.arange(C))[None, :, None]) \
            & (jnp.arange(S)[None, None, :]
               < lengths[:, None, None])
        mask = key_ok[:, None]                       # (B, 1, C, S)

        def body(x, per_layer):
            lp, kp_l, vp_l, lo = _split_per_layer(per_layer, lora)

            def attend(q, k, v):
                kp = _write_chunk(kp_l, k, page_tables, start, C)
                vp = _write_chunk(vp_l, v, page_tables, start, C)
                if prefill_attention == "kernel":
                    from ...ops.pallas.paged_attention import (
                        paged_prefill_attention)
                    if isinstance(kp, tuple):
                        ctx = paged_prefill_attention(
                            q, kp[0], vp[0], page_tables, lengths,
                            start, k_scales=kp[1], v_scales=vp[1])
                    else:
                        ctx = paged_prefill_attention(
                            q, kp, vp, page_tables, lengths, start)
                    return ctx.astype(q.dtype), (kp, vp)

                def gather(pool):
                    """(B, nkv, S, hd): gather the batch's pages FIRST,
                    dequantize only that slice — never the whole pool."""
                    if isinstance(pool, tuple):
                        data, sc = pool
                        g = (data[:, page_tables].astype(jnp.float32)
                             * sc[:, page_tables][..., None])
                    else:
                        g = pool[:, page_tables]
                    return jnp.swapaxes(g, 0, 1).reshape(B, nkv, S, hd)

                k_all, v_all = gather(kp), gather(vp)
                return _attend(cfg, q, k_all.astype(q.dtype),
                               v_all.astype(q.dtype), mask), (kp, vp)

            x, (kp, vp) = _layer_math(cfg, lp, x, pos_vec, attend,
                                      lora=lo)
            return x, (kp, vp)

        x, ys = _stack_apply(
            body, x, _scan_operand(layers, k_pools, v_pools, lora),
            scan_layers)
        k_pools, v_pools = ys
        # harvest rows whose (length-1) position lives in this chunk
        idx = jnp.clip(lengths - 1 - start, 0, C - 1)
        row = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32),
                                  1)[:, 0]
        hit = ((lengths - 1 >= start)
               & (lengths - 1 < start + C))[:, None]
        x_last = jnp.where(hit, row, x_last)
        return x_last, _tier_exit(k_pools, v_pools, _tm)

    def _write_chunk(pool_l, kv, page_tables, start, C):
        """kv (B, nkv, C, hd) written at absolute positions start.. —
        start and C are page multiples, so whole pages scatter."""
        B = kv.shape[0]
        npg = C // page_size
        first = start // page_size
        ids = jax.lax.dynamic_slice_in_dim(page_tables, first, npg,
                                           1).reshape(-1)

        def pageify(a, *trail):
            a = a.reshape((B, nkv, npg, page_size) + tuple(trail))
            order = (1, 0, 2, 3) + tuple(range(4, a.ndim))
            return jnp.transpose(a, order).reshape(
                (nkv, B * npg, page_size) + tuple(trail))

        if isinstance(pool_l, tuple):
            data, sc = pool_l
            qd, s = _q8(kv)
            return (data.at[:, ids].set(pageify(qd, hd)),
                    sc.at[:, ids].set(pageify(s)))
        return pool_l.at[:, ids].set(
            pageify(kv, hd).astype(pool_l.dtype))

    @jax.jit
    def _finish_prefill(outer, x_last, grammar=None):
        x = _rms(x_last, outer["model.norm.weight"], cfg.rms_norm_eps)
        return _emit(_gmask(_logits(cfg, outer, x), grammar))

    def prefill_chunked(outer, layers, tokens, page_tables, lengths,
                        pools, resume_from: int = 0, lora=None,
                        grammar=None):
        """``resume_from`` (a chunk multiple): skip chunks whose pages
        already hold real K/V — the prefix-cache path
        (PagedKVCache.acquire_prefix returns the cached token count;
        pass the MINIMUM across the batch, rounded DOWN to a chunk
        multiple — a larger value would skip chunks that are
        uninitialized for the less-cached sequences). The final chunk
        always runs so the last-position logits exist; its page writes
        rewrite identical content when the tail was cached.
        ``lora``: optional ``(adapter_bank, adapter_ids)`` deltas,
        threaded into every chunk call."""
        C = chunked_prefill
        B, T = tokens.shape
        if T % C:
            raise ValueError(
                f"chunked prefill: padded prompt length {T} must be a "
                f"multiple of the chunk size {C}")
        if resume_from % C:
            raise ValueError(f"resume_from {resume_from} must be a "
                             f"chunk multiple ({C})")
        resume = min(resume_from, T - C)
        x_last = jnp.zeros((B, cfg.hidden_size), dtype)
        for s in range(resume, T, C):  # static count; ONE compiled fn
            x_last, pools = _prefill_chunk(
                outer, layers, tokens[:, s:s + C], s, page_tables,
                lengths, pools, x_last, lora)
        return _finish_prefill(outer, x_last, grammar), pools

    # the shim itself is plain python; expose the jitted programs it
    # drives so the serving engine's recompile detector (obs layer:
    # program-cache growth across a call) can watch prefill too
    prefill_chunked._jit_inner = (_prefill_chunk, _finish_prefill)

    def _write_chunk_ragged(pool_l, kv, page_tables, starts, C):
        """kv (R, nkv, C, hd) written at PER-ROW absolute positions
        starts[r].. — per-row page ids gathered with take_along_axis
        instead of one shared dynamic slice. Duplicate ids across rows
        (idle rows all point at the reserved page 0; cohort rows
        rewriting a shared cached page carry identical content) make
        the scatter order unspecified but the result deterministic."""
        R = kv.shape[0]
        npg = C // page_size
        col = (starts // page_size)[:, None] + jnp.arange(npg)[None, :]
        ids = jnp.take_along_axis(page_tables, col, 1).reshape(-1)

        def pageify(a, *trail):
            a = a.reshape((R, nkv, npg, page_size) + tuple(trail))
            order = (1, 0, 2, 3) + tuple(range(4, a.ndim))
            return jnp.transpose(a, order).reshape(
                (nkv, R * npg, page_size) + tuple(trail))

        if isinstance(pool_l, tuple):
            data, sc = pool_l
            qd, s = _q8(kv)
            return (data.at[:, ids].set(pageify(qd, hd)),
                    sc.at[:, ids].set(pageify(s)))
        return pool_l.at[:, ids].set(
            pageify(kv, hd).astype(pool_l.dtype))

    @partial(jax.jit, donate_argnums=(6,))
    def _prefill_chunk_ragged(outer, layers, chunk, starts, page_tables,
                              lengths, pools, x_last, lora=None):
        """One C-token chunk PER ROW at per-row absolute positions
        starts[r]..starts[r]+C-1: a lane's pending chunks ACROSS
        requests fused into one fixed-shape program. ``starts`` rides
        as jit data exactly like decode_n's lengths, so one compiled
        program serves every admission mix. Rows with nothing to run
        point their pages at the reserved padding page 0 and write
        garbage there (the pool convention); their x_last never
        updates because length-1 falls outside the chunk window."""
        R, C = chunk.shape
        if pressure:
            col = (starts // page_size)[:, None] + jnp.arange(
                C // page_size)[None, :]
            pools = _tier_clear(
                pools, jnp.take_along_axis(page_tables, col, 1))
        k_pools, v_pools, _tm = _tier_enter(pools)
        W = page_tables.shape[1]
        S = W * page_size
        x = jnp.take(outer["model.embed_tokens.weight"], chunk, axis=0)
        pos = starts[:, None] + jnp.arange(C)[None, :]       # (R, C)
        # causal over ABSOLUTE key positions, bounded by real length —
        # the per-chunk mask with a per-row start
        key_ok = (jnp.arange(S)[None, None, :] <= pos[:, :, None]) \
            & (jnp.arange(S)[None, None, :]
               < lengths[:, None, None])
        mask = key_ok[:, None]                       # (R, 1, C, S)

        def body(x, per_layer):
            lp, kp_l, vp_l, lo = _split_per_layer(per_layer, lora)

            def attend(q, k, v):
                kp = _write_chunk_ragged(kp_l, k, page_tables, starts,
                                         C)
                vp = _write_chunk_ragged(vp_l, v, page_tables, starts,
                                         C)

                def gather(pool):
                    """(R, nkv, S, hd): gather the batch's pages FIRST,
                    dequantize only that slice — never the whole
                    pool."""
                    if isinstance(pool, tuple):
                        data, sc = pool
                        g = (data[:, page_tables].astype(jnp.float32)
                             * sc[:, page_tables][..., None])
                    else:
                        g = pool[:, page_tables]
                    return jnp.swapaxes(g, 0, 1).reshape(R, nkv, S, hd)

                k_all, v_all = gather(kp), gather(vp)
                return _attend(cfg, q, k_all.astype(q.dtype),
                               v_all.astype(q.dtype), mask), (kp, vp)

            x, (kp, vp) = _layer_math(cfg, lp, x, pos, attend, lora=lo)
            return x, (kp, vp)

        x, ys = _stack_apply(
            body, x, _scan_operand(layers, k_pools, v_pools, lora),
            scan_layers)
        k_pools, v_pools = ys
        idx = jnp.clip(lengths - 1 - starts, 0, C - 1)
        row = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32),
                                  1)[:, 0]
        hit = ((lengths - 1 >= starts)
               & (lengths - 1 < starts + C))[:, None]
        x_last = jnp.where(hit, row, x_last)
        return x_last, _tier_exit(k_pools, v_pools, _tm)

    def prefill_ragged(outer, layers, chunk, starts, page_tables,
                       lengths, pools, lora=None, grammar=None):
        """ONE fused lane dispatch: row r runs the C tokens of
        ``chunk[r]`` at absolute offset ``starts[r]`` against its own
        page table. Returns per-row next-token logits-argmax like
        ``prefill``; only rows whose FINAL chunk this is (length-1
        inside the window) carry a meaningful value — the engine reads
        exactly those rows and ignores the rest."""
        R = chunk.shape[0]
        x_last = jnp.zeros((R, cfg.hidden_size), dtype)
        x_last, pools = _prefill_chunk_ragged(
            outer, layers, chunk, starts, page_tables, lengths, pools,
            x_last, lora)
        return _finish_prefill(outer, x_last, grammar), pools

    prefill_ragged._jit_inner = (_prefill_chunk_ragged, _finish_prefill)

    if chunked_prefill is not None:
        if chunked_prefill % page_size:
            raise ValueError("chunked_prefill must be a multiple of "
                             f"page_size ({page_size})")
        prefill = prefill_chunked
        if prefill_attention != "kernel":
            # the fused program always attends via the gather path;
            # advertising it under kernel-mode prefill would silently
            # mix two numerics in one run, so the engine only sees the
            # ragged entry point when both programs share the math
            prefill_chunked._ragged = prefill_ragged

    @partial(jax.jit, donate_argnums=(5,), static_argnums=(6,))
    def decode_n(outer, layers, tok, page_tables, lengths, pools, n,
                 lora=None, grammar=None):
        """n decode steps in ONE compiled program (lax.scan over the
        step body) — the serving loop's dispatch amortizer: per-step
        python dispatch costs ~8-15 ms through a remote-PJRT tunnel
        (and ~100 us even host-local), which at B=8 buried the paged
        kernels 8x below the dense cache; scan-amortized the same
        kernels measure 1.36x dense (PERF.md round 4). With
        emit="logits" the feedback token is greedy argmax; the stacked
        per-step emissions come back as (n, B, ...) so the caller still
        owns post-hoc sampling decisions. Returns
        (emits (n, B, ...), next_tok (B,), pools'); the caller's length
        bookkeeping is lengths' = lengths + n. NOTE: ``pools`` is
        DONATED (like decode_step's) — rebind the returned pools and
        never reuse the argument, or JAX raises a donated-buffer
        error. ``lora``: optional ``(adapter_bank, adapter_ids)``
        multi-adapter deltas — both jit INPUTS, so the ONE compiled
        program serves any adapter mix (the serving_lora recompile
        gate counts exactly this cache staying at one entry).
        ``grammar``: optional ``(mask_table, state_ids)`` constrained-
        decoding masks, the same jit-input discipline. NOTE the DFA
        state advances HOST-side from each emitted token, so the mask
        holds each row's dispatch-time state for all ``n`` scanned
        steps — a wave carrying any constrained row must run ``n=1``
        (the serving engine clamps exactly this; ``n`` is static, so
        the clamp costs at most one extra cache entry, flat in the
        number of schemas)."""
        def body(carry, _):
            tok, lens, pools = carry
            nxt, pools = decode_step(outer, layers, tok, page_tables,
                                     lens, pools, lora, grammar)
            step_tok = nxt if nxt.ndim == 1 else jnp.argmax(
                nxt, -1).astype(jnp.int32)
            return (step_tok.astype(jnp.int32), lens + 1, pools), nxt
        # int32 up front: with emit="logits" callers derive the seed
        # token themselves (e.g. np.argmax -> int64) and a dtype drift
        # would break the scan carry structure
        (tok, _, pools), emits = jax.lax.scan(
            body, (jnp.asarray(tok, jnp.int32), lengths, pools), None,
            length=n)
        return emits, tok, pools

    pools = init_pools()
    if tp is not None and tp.hbm_budget_bytes_per_device is not None:
        # MEASURED per-device residency after placement (weights +
        # pools) vs the declared budget: a model too big for one
        # device's HBM must refuse loudly here, not OOM mid-serve —
        # and the same model under a wider mesh fits and serves (the
        # serving_tp capacity gate drives exactly this pair)
        need = decode_need_bytes_per_device(outer, layers, pools)
        if need > tp.hbm_budget_bytes_per_device:
            raise MemoryError(
                f"tp={tp.size}: weights + KV pool need {need} bytes "
                f"per device, budget is "
                f"{tp.hbm_budget_bytes_per_device} — widen the mesh "
                "or shrink the pool")
    return outer, layers, pools, prefill, decode_step, decode_n


def route_decode(lengths, capacity: int, shared_prefix: bool = False,
                 expect_churn: bool = False, explain: bool = False):
    """Serving router: pick the decode backend from batch statistics
    (round-4 verdict item 6 — callers previously chose by hand).

    Returns "paged" or "dense". Policy derived from the chip rows in
    PERF.md (records 27/29/34 + the round-5 compiled-decode
    re-measurement, record 37): routing is by batch STRUCTURE, not
    size — the round-4 "small batches -> paged (1.90x)" rule compared
    scan-amortized paged against a per-token-dispatched dense loop;
    with the dense loop compiled (gen.compiled) dense wins every
    uniform shape measured (B=1: 559 vs ~166 tok/s paged-per-seq
    equivalent; B=8: 3237 vs 1685; B=64: 3594 vs 3043 at the best
    page size).

    - shared prompt prefixes -> paged (prefix pages are shared across
      sequences; the dense cache replicates them per slot)
    - admission/eviction churn (continuous batching) -> paged (dense
      slots pin max_len memory for the whole batch lifetime)
    - ragged lengths -> paged (the dense cache masks but still walks
      max-length KV for every row; pages walk only real lengths)
    - severely under-full compiled capacity -> paged (dense pays
      full-capacity compute for empty slots)
    - otherwise (uniform, near-full) -> dense compiled

    ``lengths``: real sequence lengths (any array-like); ``capacity``:
    the batch size the dense cache would be compiled for.

    ``explain=True`` returns ``(backend, rule)`` where ``rule`` names
    the policy clause that fired — the serving engine's decision log
    (paddle_tpu.serving) records it so a workload bench can say WHICH
    routing rule lost when routed trails a fixed policy.
    """
    import numpy as _np

    from ...obs import metrics as _obs_metrics

    def _r(backend, rule):
        # obs counter per (clause, backend): the short label is the
        # rule text up to its parenthesized rationale — stable across
        # wording tweaks inside the parens, low-cardinality by design
        _obs_metrics.counter(
            "route_decode_total", "routing-rule firings by clause",
            rule=rule.split(" (")[0], backend=backend).inc()
        return (backend, rule) if explain else backend

    lens = _np.asarray(lengths)
    if shared_prefix:
        return _r("paged", "shared-prefix (prefix pages shared across "
                           "sequences; dense replicates per slot)")
    if expect_churn:
        return _r("paged", "churn (dense slots pin max_len memory for "
                           "the batch lifetime)")
    B = int(lens.size)
    if B == 0:
        return _r("dense", "empty wave")
    spread = float(lens.max() - lens.min()) / max(1.0, float(lens.max()))
    if spread > 0.25:
        return _r("paged", f"ragged lengths (spread {spread:.2f} > 0.25; "
                           "pages walk only real lengths)")
    if B < capacity // 2:
        return _r("paged", f"under-full (B={B} < capacity {capacity}//2; "
                           "dense pays full-capacity compute)")
    return _r("dense", "uniform near-full wave (dense compiled wins "
                       "every uniform shape measured, PERF record 37)")


class PagedOnlyDense:
    """THE dense-backend stub for paged-only serving factories (the
    TP factory below and ``serving.sim`` share it): exactly enough
    surface for ``ServingEngine.__init__``'s dense introspection —
    the ``rolling`` check and the embed-tokens dtype read — with
    every actual dense call raising ``reason``. One class, so when
    the engine grows a new introspection read there is one stub to
    keep in lockstep, not a copy per paged-only factory."""

    def __init__(self, reason: str):
        def _raise(*a, **k):
            raise NotImplementedError(reason)
        self._raise = _raise
        self._parts = {
            "rolling": False,
            "outer": {"model.embed_tokens.weight":
                      np.zeros((1, 1), np.float32)},
            "init_caches": _raise,
            "prefill": _raise,
            "decode_step": _raise,
        }

    def __call__(self, *a, **k):
        self._raise()


_TP_DENSE_REASON = (
    "a tensor-parallel serving factory is paged-only: the dense "
    "wave cache replicates max_len K/V per slot on ONE device, "
    "which is exactly the residency TP exists to break — route "
    "with policy='paged'")

_PRESSURE_DENSE_REASON = (
    "a kv_quant='pressure' serving factory is paged-only: the "
    "degradation tier compacts PAGES parked in the pool's evictable "
    "LRU, and the dense wave cache has neither pages nor an LRU — "
    "route with policy='paged'")


def llama_serving_decode_factory(model: LlamaForCausalLM,
                                 max_len: int = 256,
                                 page_size: int = 64,
                                 n_pool_pages: int = 256,
                                 kv_cache_dtype: str | None = None,
                                 batch_capacity: int = 8,
                                 scan_layers: bool = True,
                                 chunked_prefill: int | None = None,
                                 tp: "TPConfig | int | None" = None,
                                 lora: "LoRAConfig | tuple | None"
                                 = None,
                                 draft: LlamaForCausalLM | None
                                 = None,
                                 kv_quant: str | None = None,
                                 grammar: "GrammarConfig | tuple | "
                                 "None" = None):
    """Both decode backends behind one object + the router: build once,
    then ``pick(lengths, ...)`` returns ("dense", gen) or
    ("paged", (outer, layers, pools, prefill, decode_step, decode_n))
    per batch. The dense program and the paged pool coexist; routing
    per admission wave is how serving stacks exploit both regimes.

    ``batch_capacity`` is the batch size the dense compiled program is
    expected to serve (gen.compiled specializes per batch shape; this
    is the shape the serving loop pads uniform waves to). It is the
    DEFAULT ``capacity`` for ``pick`` — previously capacity defaulted
    to len(lengths), which made route_decode's under-full check
    (B < capacity//2) unreachable: a 2-request wave against an 8-slot
    compiled program now correctly routes paged."""
    # kv_cache_dtype is the SERVING cache codec: it must reach BOTH
    # backends, or an int8-configured engine would quantize only
    # paged-routed traffic (and int8 rounding can flip a greedy token,
    # breaking cross-backend output parity for no routing reason)
    tp = as_tp_config(tp)
    lora = as_lora_config(lora)
    grammar = as_grammar_config(grammar)
    if kv_quant not in (None, "int8", "pressure"):
        raise ValueError(f"kv_quant {kv_quant!r}: use None, 'int8' or "
                         "'pressure'")
    if kv_quant == "int8":
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError("kv_quant='int8' IS kv_cache_dtype="
                             f"'int8' — {kv_cache_dtype!r} conflicts")
        # the serving cache codec must reach BOTH backends (see the
        # kv_cache_dtype note below), so always-int8 rides it
        kv_cache_dtype = "int8"
    if kv_quant == "pressure":
        if kv_cache_dtype is not None:
            raise ValueError("kv_quant='pressure' owns the pool codec "
                             "— drop kv_cache_dtype")
        if draft is not None:
            raise ValueError(
                "kv_quant='pressure' does not compose with draft= "
                "yet: the draft pool rides the target's page ids but "
                "has no tier mask, so a compacted target page would "
                "desync draft K/V — use kv_quant='int8'")
    if tp is None:
        if kv_quant == "pressure":
            # pressure is PAGED-ONLY: the dense wave cache has no
            # pages to tier
            gen = PagedOnlyDense(_PRESSURE_DENSE_REASON)
        else:
            gen = llama_decode_factory(model, max_len=max_len,
                                       kv_cache_dtype=kv_cache_dtype,
                                       scan_layers=scan_layers)
    else:
        # tensor-parallel serving is PAGED-ONLY: no dense replica is
        # built (see PagedOnlyDense) — the engine coerces its routing
        # to the paged backend
        gen = PagedOnlyDense(_TP_DENSE_REASON)
    paged = llama_paged_decode_factory(model, page_size=page_size,
                                       n_pool_pages=n_pool_pages,
                                       kv_cache_dtype=kv_cache_dtype,
                                       chunked_prefill=chunked_prefill,
                                       scan_layers=scan_layers, tp=tp,
                                       lora=lora, kv_quant=kv_quant)
    lora_hooks = None
    if lora is not None:
        # the adapter-cache device hooks (serving.adapters.AdapterCache
        # consumes them); dtype follows the decode weights
        lora_hooks = lora_bank_hooks(
            model.config, lora,
            paged[1]["self_attn.q_proj.weight"].dtype, tp=tp)
    grammar_hooks = None
    if grammar is not None:
        # the grammar-cache device hooks (serving.grammar.GrammarCache
        # consumes them); under tp the bank replicates on the mesh
        grammar_hooks = grammar_bank_hooks(model.config.vocab_size,
                                           grammar, tp=tp)
    spec_built = None
    if draft is not None:
        # SPECULATIVE serving: the draft model gets its own paged
        # parts over the SAME page geometry — its pool is indexed by
        # the target's page ids, so draft K/V rides the target's
        # PagedKVCache chains (one allocation per request covers
        # both; prefix retention and eviction recycle draft pages in
        # lockstep with target pages). The batched spec round program
        # (draft propose + target verify + branch-free acceptance)
        # comes from build_spec_step.
        if lora is not None:
            raise ValueError(
                "speculative serving does not compose with lora= yet "
                "— the draft has no adapter bank, so a per-row delta "
                "would desync draft proposals from the verified "
                "target (run spec engines single-model)")
        if draft.config.vocab_size != model.config.vocab_size:
            raise ValueError("target and draft must share a "
                             "vocabulary")
        d_outer, d_layers, d_pools, d_prefill, _, _ = \
            llama_paged_decode_factory(
                draft, page_size=page_size, n_pool_pages=n_pool_pages,
                chunked_prefill=chunked_prefill,
                scan_layers=scan_layers)
        if tp is not None:
            # the draft REPLICATES on the target's mesh (no partition
            # specs = every device holds the whole draft): a draft is
            # small by construction, and a replicated draft walk
            # needs zero collectives — only the sharded target verify
            # pays the per-block psums
            mesh = tp.build_mesh()
            d_outer = device_put_sharded(d_outer, mesh)
            d_layers = device_put_sharded(d_layers, mesh)
            d_pools = device_put_sharded(d_pools, mesh)
        spec_built = (d_outer, d_layers, d_pools, d_prefill,
                      build_spec_step(model.config, draft.config,
                                      page_size, scan_layers))

    class _Serving:
        # staticmethod: a bare function class-attribute would BIND as a
        # method and eat the first positional arg (tokens) as self
        dense = staticmethod(gen)
        paged_parts = paged
        capacity = batch_capacity
        # build-config metadata the serving engine reads when handed a
        # prebuilt factory (paddle_tpu.serving.ServingEngine(serving=...))
        max_len_ = max_len
        page_size_ = page_size
        n_pool_pages_ = n_pool_pages
        chunked_prefill_ = chunked_prefill
        tp_ = tp  # TPConfig when the paged path is mesh-sharded
        lora_ = lora  # LoRAConfig when multi-adapter serving is built
        # GrammarConfig when constrained decoding is built, plus the
        # vocabulary size the engine compiles schemas against
        grammar_ = grammar
        grammar_vocab_ = model.config.vocab_size
        # quantized page tier: None | "int8" | "pressure". page_bytes_
        # prices ONE page (full-precision, int8+scale) for the
        # bookkeeper's stored-bytes census; the pressure hooks are the
        # device-side compaction/handoff programs the engine drives.
        kv_quant_ = kv_quant
        page_bytes_ = (kv_quant_page_bytes(
            model.config, page_size,
            paged[1]["self_attn.q_proj.weight"].dtype)
            if kv_quant is not None else None)
        if kv_quant == "pressure":
            compact_pages = staticmethod(compact_kv_pages)
            export_kv_pages = staticmethod(export_quant_pages)
            import_kv_pages = staticmethod(import_quant_pages)
        # (draft outer, layers, pools, chunked prefill, spec_step)
        # when the factory is spec-capable; None otherwise — the
        # engine refuses ServingEngine(spec=...) without it. A tuple,
        # not a callable, so the class attribute never method-binds.
        spec_parts = spec_built
        if getattr(paged[3], "_ragged", None) is not None:
            # the fused ragged-prefill entry point (one program for a
            # whole lane turn); absent when the per-chunk prefill uses
            # kernel attention, so the engine's ragged_prefill= flag
            # fails loudly instead of mixing numerics
            prefill_ragged = staticmethod(paged[3]._ragged)
        if lora_hooks is not None:
            # adapter-cache device hooks (paddle_tpu.serving.adapters)
            init_adapter_bank = staticmethod(lora_hooks[0])
            upload_adapter = staticmethod(lora_hooks[1])
        if grammar_hooks is not None:
            # grammar-cache device hooks (paddle_tpu.serving.grammar)
            init_grammar_bank = staticmethod(grammar_hooks[0])
            upload_grammar = staticmethod(grammar_hooks[1])

        def pick(self, lengths, capacity=None, shared_prefix=False,
                 expect_churn=False):
            if self.tp_ is not None or self.kv_quant_ == "pressure":
                # no dense replica exists on a sharded or
                # pressure-tiered factory
                return "paged", paged
            # read the live attribute (not the factory closure) so
            # callers who adjust serving.capacity see routing follow
            cap = capacity if capacity is not None else self.capacity
            backend = route_decode(lengths, cap, shared_prefix,
                                   expect_churn)
            return backend, (gen if backend == "dense" else paged)

    return _Serving()
