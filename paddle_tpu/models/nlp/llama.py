"""Llama family — the flagship hybrid-parallel model (BASELINE config 4).

Capability slot of the PaddleNLP llm/ Llama recipe running on the
reference's fleet 4D parallelism (SURVEY.md §2.2). TPU-native design:

  * nn.Layer model built from the TP parallel layers (GSPMD sharding
    annotations on weights: attention/ffn column+row split over 'model',
    embeddings over vocab) — the eager / checkpoint-compatible surface.
  * ``llama_train_step_factory``: the compiled path. Takes a Mesh with axes
    (data, sep, model) [+ pipe via paddle_tpu.parallel.pipeline], lays out
    params by their sharding_spec, shards the batch on 'data' and the
    sequence on 'sep' (context parallelism — EXCEEDS the reference, which
    has no sequence parallel, SURVEY.md §5), and returns a jitted
    loss+grad+adamw step. XLA inserts all collectives (psum over 'model'
    for row-parallel matmuls, all_gathers for column outputs, grad psums
    over 'data') — the role of the reference's hand-written
    c_allreduce/reducer stack.

Architecture (standard Llama-3): RMSNorm pre-norm, rotary embeddings, GQA,
SwiGLU MLP, tied-off LM head, causal flash attention (Pallas kernel on the
jit path).
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import nn
from ...core.tensor import Parameter, Tensor
from ...distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                                RowParallelLinear,
                                                VocabParallelEmbedding)
from ...jax_compat import shard_map as _shard_map
from ...nn import functional as F
from ...ops.dispatch import apply_op


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # fuse q/k/v (and gate/up) into single wider matmuls — fewer, larger
    # MXU calls (~ reference fused_attention's qkv packing); weight names
    # change (qkv_proj / gate_up_proj), so default off for ckpt compat
    fuse_attention_qkv: bool = False
    fuse_ffn_gate_up: bool = False
    # Mistral-style sliding-window attention (tokens; None = full causal).
    # Flash-eligible shapes run the splash kernel over a banded block
    # pattern — compute scales with window/S, not S^2; small shapes apply
    # the window in the dense path.
    sliding_window: int | None = None

    @staticmethod
    def llama3_8b():
        return LlamaConfig(vocab_size=128256, hidden_size=4096,
                           intermediate_size=14336, num_hidden_layers=32,
                           num_attention_heads=32, num_key_value_heads=8,
                           rope_theta=500000.0)

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2):
        return LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                           intermediate_size=hidden * 2,
                           num_hidden_layers=layers,
                           num_attention_heads=heads,
                           num_key_value_heads=kv_heads,
                           max_position_embeddings=512, dtype=jnp.float32)


# --- context parallelism ---------------------------------------------------
# When set (by the train-step factories, or explicitly via
# set_context_parallel_mesh), LlamaAttention runs ring attention over the
# 'sep' axis (parallel/ring_attention.py: KV ppermute + online softmax)
# instead of the dense S x S einsum — without this the 'sep' sharding of the
# batch buys nothing, as XLA must all-gather the sequence for the einsum.
_CP = {"mesh": None, "axis": "sep"}
_TP = {"mesh": None, "axis": "model"}


def set_tensor_parallel_mesh(mesh, axis: str = "model"):
    """Mesh whose `axis` shards attention heads (set by the train-step
    factories). Needed because GSPMD cannot partition a Pallas custom
    call: without it, flash attention under TP forces per-layer
    all-gathers of Q/K/V (measured: 140 all-gathers vs 0 on a 2-layer
    TP=2 program). With it, the flash call runs inside a partial-manual
    shard_map over `axis` — per-device kernels on local heads."""
    _TP["mesh"] = mesh
    _TP["axis"] = axis


def _tensor_parallel_mesh():
    mesh, axis = _TP["mesh"], _TP["axis"]
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] <= 1:
        return None, None
    return mesh, axis


def _shard_map_heads(fn, mesh, axis, *qkv, batch_axis="data"):
    """Shared wrapper (parallel/pallas_sharding.py): heads manual over
    `axis`, batch over `batch_axis` when divisible — GSPMD can't
    partition a Pallas call over either dim."""
    from ...parallel.pallas_sharding import shard_map_attention
    return shard_map_attention(fn, *qkv, mesh=mesh, head_axis=axis,
                               batch_axis=batch_axis)


def set_context_parallel_mesh(mesh, axis: str = "sep"):
    """Install the mesh used for ring attention (None disables)."""
    _CP["mesh"] = mesh
    _CP["axis"] = axis


def _context_parallel_mesh():
    mesh, axis = _CP["mesh"], _CP["axis"]
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        return mesh, axis
    from ...distributed.topology import get_global_mesh
    g = get_global_mesh()
    if g is not None and g.shape.get("sep", 1) > 1:
        return g, "sep"
    return None, None


def _dense_attention_tail(qt, kt, vt, scale, window=None):
    """The one dense causal-softmax path (flash-ineligible shapes), with
    the optional sliding-window band folded into its mask."""
    S = qt.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    i = jnp.arange(S)[:, None]
    j = jnp.arange(S)[None, :]
    live = i >= j
    if window is not None:
        live = live & (i - j < window)
    s = jnp.where(live, s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(qt.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vt)


def _flash_eligible(seq_len: int, head_dim: int, dtype) -> bool:
    """Delegates to the ops-layer gate (shared with Ulysses/ring so the
    model and sequence-parallel entries can never diverge)."""
    from ...ops.pallas.flash_attention import flash_eligible
    return flash_eligible(seq_len, head_dim, dtype)


def _rope_freqs(head_dim, theta):
    return 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))


def apply_rotary(x, positions, theta):
    """x: (..., seq, heads, head_dim)."""
    head_dim = x.shape[-1]
    freqs = jnp.asarray(_rope_freqs(head_dim, theta), jnp.float32)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (s, hd/2)
    cos = jnp.cos(angles)[..., None, :]  # (s, 1, hd/2)
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.rope_theta = c.rope_theta
        self.sliding_window = getattr(c, "sliding_window", None)
        if self.sliding_window is not None and self.sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1 (got "
                f"{self.sliding_window}); use None to disable")
        self.fused_qkv = bool(getattr(c, "fuse_attention_qkv", False))
        kv_out = self.num_kv_heads * self.head_dim
        if self.fused_qkv:
            # one (H, H + 2*kv) matmul instead of three — fewer, larger
            # MXU calls (the reference fused_attention_op's QKV packing)
            self.qkv_proj = ColumnParallelLinear(
                c.hidden_size, c.hidden_size + 2 * kv_out, has_bias=False)
        else:
            self.q_proj = ColumnParallelLinear(c.hidden_size, c.hidden_size,
                                               has_bias=False)
            self.k_proj = ColumnParallelLinear(c.hidden_size, kv_out,
                                               has_bias=False)
            self.v_proj = ColumnParallelLinear(c.hidden_size, kv_out,
                                               has_bias=False)
        self.o_proj = RowParallelLinear(c.hidden_size, c.hidden_size,
                                        has_bias=False)

    def forward(self, x, positions=None):
        B, S, H = x.shape
        kv_out = self.num_kv_heads * self.head_dim
        if self.fused_qkv:
            qkv = self.qkv_proj(x)
            q = qkv[:, :, :H].reshape([B, S, self.num_heads, self.head_dim])
            k = qkv[:, :, H:H + kv_out].reshape(
                [B, S, self.num_kv_heads, self.head_dim])
            v = qkv[:, :, H + kv_out:].reshape(
                [B, S, self.num_kv_heads, self.head_dim])
        else:
            q = self.q_proj(x).reshape(
                [B, S, self.num_heads, self.head_dim])
            k = self.k_proj(x).reshape(
                [B, S, self.num_kv_heads, self.head_dim])
            v = self.v_proj(x).reshape(
                [B, S, self.num_kv_heads, self.head_dim])

        theta = self.rope_theta
        n_rep = self.num_heads // self.num_kv_heads

        window = self.sliding_window

        def attn(qv, kv, vv):
            pos = jnp.arange(S) if positions is None else positions
            qv = apply_rotary(qv, pos, theta)
            kv = apply_rotary(kv, pos, theta)
            scale = 1.0 / math.sqrt(qv.shape[-1])

            if window is not None and window < S:
                cp_mesh, cp_axis = _context_parallel_mesh()
                if cp_mesh is not None \
                        and S % cp_mesh.shape[cp_axis] == 0:
                    # window x 'sep' compose (round-4 verdict item 5):
                    # the window-aware ring walks only the chunk pairs
                    # the band touches (per-pair banded splash with a
                    # shifted query frame); K/V rotate at their true
                    # head count unless TP head sharding forbids it
                    mdl_sz = (cp_mesh.shape["model"]
                              if "model" in cp_mesh.axis_names else 1)
                    kvr, vvr = kv, vv
                    if n_rep > 1 and kv.shape[2] % max(1, mdl_sz) != 0:
                        kvr = jnp.repeat(kv, n_rep, axis=2)
                        vvr = jnp.repeat(vv, n_rep, axis=2)
                    from ...parallel.ring_attention import \
                        ring_window_attention
                    out = ring_window_attention(
                        jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kvr, 1, 2),
                        jnp.swapaxes(vvr, 1, 2), cp_mesh, window,
                        axis=cp_axis, sm_scale=scale,
                        batch_axis="data", head_axis="model")
                    return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)
                from ...ops.pallas.splash_attention import \
                    fits_score_budget
                if n_rep > 1 and _flash_eligible(S, qv.shape[-1],
                                                 qv.dtype) \
                        and fits_score_budget(n_rep):
                    # grouped banded splash: K/V stay at the true kv-head
                    # count AND compute scales with window/S (very large
                    # groups exceed the kernel's VMEM score budget and
                    # fall through to the repeat path below)
                    from ...ops.pallas.splash_attention import (
                        banded_block_mask, grouped_splash_attention,
                        pick_splash_blocks)
                    sbq, sbk = pick_splash_blocks(S, S, n_rep)
                    bm = banded_block_mask(S, S, sbq, sbk, window)
                    tp_mesh, tp_axis = _tensor_parallel_mesh()
                    out = _shard_map_heads(
                        lambda q, k, v: grouped_splash_attention(
                            q, k, v, bm, True, scale, sbq, sbk, window),
                        tp_mesh, tp_axis or "model",
                        jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kv, 1, 2),
                        jnp.swapaxes(vv, 1, 2))
                    return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)
                kvw, vvw = kv, vv
                if n_rep > 1:
                    kvw = jnp.repeat(kv, n_rep, axis=2)
                    vvw = jnp.repeat(vv, n_rep, axis=2)
                qt = jnp.swapaxes(qv, 1, 2)
                kt = jnp.swapaxes(kvw, 1, 2)
                vt = jnp.swapaxes(vvw, 1, 2)
                if _flash_eligible(S, qt.shape[-1], qt.dtype):
                    # banded splash: compute scales with window/S
                    from ...ops.pallas.splash_attention import (
                        banded_block_mask, pick_splash_blocks,
                        splash_attention)
                    sbq, sbk = pick_splash_blocks(S, S)
                    bm = banded_block_mask(S, S, sbq, sbk, window)
                    tp_mesh, tp_axis = _tensor_parallel_mesh()
                    out = _shard_map_heads(
                        lambda q, k, v: splash_attention(
                            q, k, v, bm, True, scale, sbq, sbk, window),
                        tp_mesh, tp_axis or "model", qt, kt, vt)
                    return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)
                out = _dense_attention_tail(qt, kt, vt, scale, window)
                return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)

            # GQA fast path: the grouped kernel keeps K/V at their true
            # head count (no n_rep x HBM/VMEM blowup from jnp.repeat)
            use_flash_gqa = (n_rep > 1
                             and _flash_eligible(qv.shape[1], qv.shape[-1],
                                                 qv.dtype)
                             and _context_parallel_mesh()[0] is None)
            if use_flash_gqa:
                from ...ops.pallas.flash_attention_gqa import (
                    grouped_flash_attention)
                tp_mesh, tp_axis = _tensor_parallel_mesh()
                # the wrapper self-guards divisibility and falls back to a
                # plain call; mesh=None probes the context abstract mesh
                out = _shard_map_heads(
                    lambda q, k, v: grouped_flash_attention(
                        q, k, v, True, scale),
                    tp_mesh, tp_axis or "model",
                    jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kv, 1, 2),
                    jnp.swapaxes(vv, 1, 2))
                return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)

            cp_mesh, cp_axis = _context_parallel_mesh()
            if cp_mesh is not None and S % cp_mesh.shape[cp_axis] == 0:
                from ...core import flags as _flags
                backend = _flags.get_flag("context_parallel_backend")
                if backend == "ulysses" and \
                        qv.shape[2] % cp_mesh.shape[cp_axis] == 0:
                    # ulysses all-to-alls the head dim — needs full heads
                    kvr = jnp.repeat(kv, n_rep, axis=2) if n_rep > 1 else kv
                    vvr = jnp.repeat(vv, n_rep, axis=2) if n_rep > 1 else vv
                    from ...parallel.ulysses import ulysses_attention
                    out = ulysses_attention(
                        jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kvr, 1, 2),
                        jnp.swapaxes(vvr, 1, 2), cp_mesh, axis=cp_axis,
                        causal=True, sm_scale=scale)
                else:
                    # ring rotates K/V at their TRUE head count (GQA: G x
                    # less ICI traffic) unless the kv heads don't divide
                    # the TP axis sharding
                    mdl_sz = (cp_mesh.shape["model"]
                              if "model" in cp_mesh.axis_names else 1)
                    kvr, vvr = kv, vv
                    if n_rep > 1 and kv.shape[2] % max(1, mdl_sz) != 0:
                        kvr = jnp.repeat(kv, n_rep, axis=2)
                        vvr = jnp.repeat(vv, n_rep, axis=2)
                    from ...parallel.ring_attention import ring_attention
                    out = ring_attention(
                        jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kvr, 1, 2),
                        jnp.swapaxes(vvr, 1, 2), cp_mesh, axis=cp_axis,
                        causal=True, sm_scale=scale,
                        batch_axis="data", head_axis="model")
                return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)

            if n_rep > 1:
                kv = jnp.repeat(kv, n_rep, axis=2)
                vv = jnp.repeat(vv, n_rep, axis=2)
            qt = jnp.swapaxes(qv, 1, 2)
            kt = jnp.swapaxes(kv, 1, 2)
            vt = jnp.swapaxes(vv, 1, 2)

            if _flash_eligible(S, qt.shape[-1], qt.dtype):
                # no silent fallback: a failing kernel must raise, not
                # quietly degrade to the O(S^2) path (round-1 verdict)
                from ...ops.pallas.flash_attention import flash_attention
                tp_mesh, tp_axis = _tensor_parallel_mesh()
                out = _shard_map_heads(
                    lambda q, k, v: flash_attention(q, k, v, True, scale),
                    tp_mesh, tp_axis or "model", qt, kt, vt)
                return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)
            out = _dense_attention_tail(qt, kt, vt, scale)
            return jnp.swapaxes(out, 1, 2).reshape(B, S, -1)

        ctx = apply_op("llama_attention", attn, q, k, v)
        return self.o_proj(ctx)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.fused_gate_up = bool(getattr(c, "fuse_ffn_gate_up", False))
        self.intermediate = c.intermediate_size
        if self.fused_gate_up:
            self.gate_up_proj = ColumnParallelLinear(
                c.hidden_size, 2 * c.intermediate_size, has_bias=False)
        else:
            self.gate_proj = ColumnParallelLinear(c.hidden_size,
                                                  c.intermediate_size,
                                                  has_bias=False)
            self.up_proj = ColumnParallelLinear(c.hidden_size,
                                                c.intermediate_size,
                                                has_bias=False)
        self.down_proj = RowParallelLinear(c.intermediate_size, c.hidden_size,
                                           has_bias=False)

    def forward(self, x):
        if self.fused_gate_up:
            gu = self.gate_up_proj(x)
            gate = gu[..., :self.intermediate]
            up = gu[..., self.intermediate:]
            return self.down_proj(F.silu(gate) * up)
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, positions=None):
        x = x + self.self_attn(self.input_layernorm(x), positions)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, positions=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, positions)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(config.hidden_size,
                                                config.vocab_size,
                                                has_bias=False)

    def forward(self, input_ids, positions=None):
        h = self.model(input_ids, positions)
        if self.lm_head is None:
            from ...ops.linalg import matmul
            return matmul(h, self.model.embed_tokens.weight,
                          transpose_y=True)
        return self.lm_head(h)

    # -- generation (greedy, incremental) ----------------------------------
    def generate(self, input_ids, max_new_tokens=16):
        from ...autograd import no_grad
        out = input_ids
        with no_grad():
            for _ in range(max_new_tokens):
                logits = self(out)
                nxt = logits[:, -1].argmax(-1)
                from ...ops.manipulation import concat, unsqueeze
                out = concat([out, unsqueeze(nxt, 1)], axis=1)
        return out


# ---------------------------------------------------------------------------
# Compiled GSPMD training path
# ---------------------------------------------------------------------------

def param_shardings(model: nn.Layer, mesh: Mesh) -> Dict[str, NamedSharding]:
    """Build NamedShardings from the layers' sharding_spec annotations,
    keeping only axes that exist in the mesh (degenerate axes drop out)."""
    out = {}
    for name, p in model.state_dict().items():
        spec = getattr(p, "sharding_spec", None)
        if spec is None:
            out[name] = NamedSharding(mesh, P())
        else:
            fixed = []
            for s in spec:
                if s is None or s in mesh.axis_names:
                    fixed.append(s)
                else:
                    fixed.append(None)
            out[name] = NamedSharding(mesh, P(*fixed))
    return out


def llama_train_step_factory(model: LlamaForCausalLM, mesh: Mesh,
                             learning_rate=1e-4, weight_decay=0.01,
                             beta1=0.9, beta2=0.95, eps=1e-8,
                             accum_dtype=jnp.float32,
                             remat: bool | str = True,
                             offload_moments: bool = False,
                             chunked_vocab_ce: int | None = None):
    """Returns (params, opt_state, train_step) for pjit execution.

    Shardings: params per annotation; adamw moments mirror the params but
    additionally sharded over 'sharding' axis if present (ZeRO-1); batch on
    'data'; sequence on 'sep' (context parallel).

    remat: False = no rematerialization (fastest when activations fit HBM
    — measured 0.55 vs 0.42 MFU on v5e for the 0.5B bench config);
    True = full jax.checkpoint (lowest memory, ~33% extra FLOPs);
    "dots" = selective policy saving matmul outputs and recomputing
    elementwise ops (the middle ground, ~9% over full remat).

    offload_moments: place adamw moments in pinned host memory and declare
    the memory kind in the jit's in/out shardings — XLA streams them
    across PCIe around the update (~ group_sharded_stage3.py:58 offload);
    the config every >1B single-chip model needs (f32 moments are 8 bytes
    per param — more than v5e HBM above ~2B params).

    chunked_vocab_ce: chunk size for the fused head-projection+CE
    (ops/chunked_ce.py) — the (B*S, V) logits tensor is never
    materialized (~4.2 GB bf16 at Llama-3's V=128256, B=8, S=2048, plus
    three HBM round-trips); requires tied embeddings and no >1 'model'
    axis (vocab-sharded logits already avoid the gather via the dense
    GSPMD path).
    """
    config = model.config
    if chunked_vocab_ce and model.lm_head is not None:
        raise ValueError("chunked_vocab_ce requires tied word embeddings "
                         "(the (V, H) embedding doubles as the head)")
    if chunked_vocab_ce and "model" in mesh.axis_names \
            and mesh.shape["model"] > 1:
        raise ValueError(
            "chunked_vocab_ce is a single-chip/vocab-replicated path; "
            "with a >1 'model' axis the vocab-sharded dense CE already "
            "avoids the (B*S, V) gather — drop the flag there")
    shardings = param_shardings(model, mesh)
    # copy defensively: device_put to an identical sharding would alias the
    # model's own buffers, and the donated train step would delete them
    params = {k: jax.device_put(jnp.array(v._value, copy=True), shardings[k])
              for k, v in model.state_dict().items()}

    from .train_utils import (adamw_update, make_adamw_state,
                              with_memory_kind)
    opt_state = make_adamw_state(mesh, shardings, params, accum_dtype,
                                 offload=offload_moments)

    batch_sharding = NamedSharding(
        mesh, P("data" if "data" in mesh.axis_names else None,
                "sep" if "sep" in mesh.axis_names else None))

    has_sep = "sep" in mesh.axis_names and mesh.shape["sep"] > 1

    has_model = "model" in mesh.axis_names and mesh.shape["model"] > 1

    def forward_loss(params, tokens, labels):
        from ...autograd import no_grad
        saved = model.tree_flatten_params()
        model.load_tree(params)
        prev = (_CP["mesh"], _CP["axis"])
        prev_tp = (_TP["mesh"], _TP["axis"])
        set_context_parallel_mesh(mesh if has_sep else None)
        # GSPMD can't partition Pallas calls: give the attention the mesh
        # so the flash kernel runs shard_mapped over 'model' (no Q/K/V
        # all-gathers under TP)
        set_tensor_parallel_mesh(mesh if (has_model and not has_sep)
                                 else None)
        use_chunked = bool(chunked_vocab_ce) and not has_model
        try:
            # tape off: jax.value_and_grad differentiates this trace; the
            # eager tape's per-op jax.vjp would otherwise nest a second
            # linearization around the Pallas custom_vjp kernels
            with no_grad():
                if use_chunked:
                    h = model.model(Tensor(tokens))._value
                    w_head = model.model.embed_tokens.weight._value
                else:
                    logits = model(Tensor(tokens))._value
        finally:
            model.load_tree(saved)  # don't leave tracers in the Layer
            set_context_parallel_mesh(prev[0], prev[1])
            set_tensor_parallel_mesh(prev_tp[0], prev_tp[1])
        if use_chunked:
            from ...ops.chunked_ce import chunked_causal_lm_loss
            return chunked_causal_lm_loss(h, w_head, labels,
                                          int(chunked_vocab_ce))
        if jax.default_backend() != "cpu" and not has_model:
            # Pallas fused softmax-xent: skips the (B*S, V) softmax HBM
            # round trip (the largest intermediate of the training loss).
            # GSPMD can't partition the Pallas call, so batch/sequence
            # mesh axes go manual (per-shard mean + pmean == global mean:
            # no label shift, equal shard sizes). With a >1 'model' axis
            # the logits are vocab-sharded — the dense path below is the
            # right form there (GSPMD partitions the log_softmax
            # reductions with psums instead of gathering (B,S,V)).
            from ...ops.pallas.fused_ce import causal_lm_loss
            B_, S_ = labels.shape
            dim_for = {"data": B_, "sep": S_}
            manual = [a for a in ("data", "sep")
                      if a in mesh.axis_names and mesh.shape[a] > 1
                      and dim_for[a] % mesh.shape[a] == 0]
            if not manual:
                return causal_lm_loss(logits, labels)

            def _fused(lg, lb):
                loss = causal_lm_loss(lg, lb)
                for a in manual:
                    loss = jax.lax.pmean(loss, a)
                return loss

            b_ax = "data" if "data" in manual else None
            s_ax = "sep" if "sep" in manual else None
            return _shard_map(
                _fused, mesh=mesh,
                in_specs=(P(b_ax, s_ax, None), P(b_ax, s_ax)),
                out_specs=P(), check_vma=False,
                axis_names=frozenset(manual))(logits, labels)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
        return jnp.mean(nll)

    loss_fn = forward_loss
    if remat == "dots":
        loss_fn = jax.checkpoint(
            forward_loss,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif remat:
        loss_fn = jax.checkpoint(forward_loss)

    # Host-offloaded moments, two lowerings:
    #  - TPU: fetched to device INSIDE the jit (jax memories pattern —
    #    compute can't mix host/device operands); out_shardings carry the
    #    pinned_host kind, so XLA emits both DMAs and schedules them
    #    around the update.
    #  - CPU (tests): the placement custom-call isn't implemented, so the
    #    step wrapper stages moments outside the jit — functionally
    #    identical, exercised by the CPU suite.
    moment_dev_sh = {k: with_memory_kind(opt_state["m"][k].sharding,
                                         "device")
                     for k in params} if offload_moments else None
    in_jit_offload = offload_moments and jax.default_backend() != "cpu"

    host_m_sh = {k: opt_state["m"][k].sharding
                 for k in params} if offload_moments else None

    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)
        new_p, new_m, new_v = {}, {}, {}
        if not in_jit_offload:
            for k in params:
                new_p[k], new_m[k], new_v[k] = adamw_update(
                    params[k], grads[k], opt_state["m"][k],
                    opt_state["v"][k], t, learning_rate, beta1, beta2,
                    eps, weight_decay, accum_dtype)
            return new_p, {"step": step, "m": new_m, "v": new_v}, loss
        # In-jit offload: the naive form (fetch every moment with
        # device_put, update, store) lets XLA hoist ALL fetches to the
        # start of the schedule — the fetch DMAs depend only on jit
        # inputs — so the full f32 moment set lands in HBM at once
        # (measured: 1.9B params / 15.2G moments OOM a 15.75G v5e even
        # with full remat). Chunk the update and thread an
        # optimization_barrier token host-store -> next-chunk-fetch so
        # at most one chunk of moments is device-resident at a time;
        # within a chunk XLA still overlaps DMA with the elementwise
        # update.
        keys = list(params)
        token = t
        chunk_n = 4
        for i in range(0, len(keys), chunk_n):
            chunk = keys[i:i + chunk_n]
            fetched = {}
            for k in chunk:
                m_h, v_h, _ = jax.lax.optimization_barrier(
                    (opt_state["m"][k], opt_state["v"][k], token))
                fetched[k] = (jax.device_put(m_h, moment_dev_sh[k]),
                              jax.device_put(v_h, moment_dev_sh[k]))
            for k in chunk:
                m, v = fetched[k]
                new_p[k], m_d, v_d = adamw_update(
                    params[k], grads[k], m, v,
                    t, learning_rate, beta1, beta2, eps, weight_decay,
                    accum_dtype)
                new_m[k] = jax.device_put(m_d, host_m_sh[k])
                new_v[k] = jax.device_put(v_d, host_m_sh[k])
            *arrs, token = jax.lax.optimization_barrier(
                tuple(new_m[k] for k in chunk)
                + tuple(new_v[k] for k in chunk) + (token,))
            for j, k in enumerate(chunk):
                new_m[k] = arrs[j]
                new_v[k] = arrs[len(chunk) + j]
        return new_p, {"step": step, "m": new_m, "v": new_v}, loss

    if offload_moments and not in_jit_offload:
        # CPU staging path: the jit sees device-resident moments
        jit_m_sh = moment_dev_sh
    else:
        jit_m_sh = host_m_sh or {
            k: opt_state["m"][k].sharding for k in params}
    jitted = jax.jit(
        train_step,
        in_shardings=(shardings,
                      {"step": NamedSharding(mesh, P()),
                       "m": jit_m_sh, "v": jit_m_sh},
                      batch_sharding, batch_sharding),
        out_shardings=(shardings,
                       {"step": NamedSharding(mesh, P()),
                        "m": jit_m_sh, "v": jit_m_sh},
                       NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )
    if offload_moments and not in_jit_offload:
        host_sh = host_m_sh

        def staged_step(params, opt_state, tokens, labels):
            staged = dict(
                opt_state,
                m={k: jax.device_put(x, moment_dev_sh[k])
                   for k, x in opt_state["m"].items()},
                v={k: jax.device_put(x, moment_dev_sh[k])
                   for k, x in opt_state["v"].items()})
            new_p, new_o, loss = jitted(params, staged, tokens, labels)
            new_o = dict(
                new_o,
                m={k: jax.device_put(x, host_sh[k])
                   for k, x in new_o["m"].items()},
                v={k: jax.device_put(x, host_sh[k])
                   for k, x in new_o["v"].items()})
            return new_p, new_o, loss
        return params, opt_state, staged_step, batch_sharding
    return params, opt_state, jitted, batch_sharding
