"""Model zoo. Vision lives in paddle_tpu.vision.models (hapi layout); NLP
model families (BERT/GPT/Llama/MoE — the PaddleNLP capability slots) here."""
from . import nlp  # noqa: F401
