"""Automatic mixed precision.

~ python/paddle/amp/ (auto_cast.py:21, grad_scaler.py:26) + the C++ op
allow/block lists (paddle/fluid/imperative/amp_auto_cast.h:44, AmpLevel O1/O2
:29). TPU-native difference: the low-precision dtype is bfloat16, which has
fp32-range exponent — so loss scaling is a no-op by default (GradScaler keeps
the dynamic-scaling machinery for fp16 compat and API parity, but with bf16
``use_loss_scaling=False`` paths are exercised).

Mechanism: an AMP state consulted by the op dispatcher (ops/dispatch.py);
white-listed ops cast float32 inputs down, black-listed ops force float32 —
the same pre-kernel cast insertion TraceOp does in the reference.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core import dtype as _dt
from ..core.tensor import Tensor

_state = threading.local()

# ~ imperative/amp_auto_cast.cc AmpOperators default lists
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "einsum", "linear", "conv1d", "conv2d",
    "conv3d", "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "scaled_dot_product_attention", "addmm",
}
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos",
    "sin", "tan", "norm", "cross_entropy", "softmax_with_cross_entropy",
    "bce_with_logits", "binary_cross_entropy", "layer_norm", "rms_norm",
    "batch_norm", "softmax", "log_softmax", "cumsum", "logsumexp", "erf",
    "erfinv", "pow", "mse_loss", "l1_loss", "kl_div",
}


def amp_state():
    return getattr(_state, "amp", None)


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """~ paddle.amp.auto_cast (amp/auto_cast.py:21)."""
    if not enable:
        prev = amp_state()
        _state.amp = None
        try:
            yield
        finally:
            _state.amp = prev
        return
    white = set(WHITE_LIST)
    black = set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    prev = amp_state()
    _state.amp = {
        "level": level,
        "dtype": _dt.convert_dtype(dtype),
        "white": white,
        "black": black,
    }
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def _maybe_cast(op_name: str, vals):
    """Called from ops.dispatch.apply_op on every op when AMP is active."""
    st = amp_state()
    if st is None:
        return vals
    low = st["dtype"]
    if op_name in st["white"] or st["level"] == "O2" and op_name not in st["black"]:
        return [v.astype(low)
                if hasattr(v, "dtype") and v.dtype == jnp.float32 else v
                for v in vals]
    if op_name in st["black"]:
        return [v.astype(jnp.float32)
                if hasattr(v, "dtype") and v.dtype == jnp.dtype(low) else v
                for v in vals]
    return vals


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """~ paddle.amp.decorate (auto_cast.py:81). O2 casts model params low."""
    single = not isinstance(models, (list, tuple))
    ms = [models] if single else list(models)
    if level == "O2":
        for m in ms:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single else ms
    # O2 + master_weight (reference default: None means True at O2):
    # optimizers keep f32 masters for the now-low-precision params
    if level == "O2" and master_weight is not False:
        opts = (optimizers if isinstance(optimizers, (list, tuple))
                else [optimizers])
        for o in opts:
            o._multi_precision = True
            # Retrofit masters for accumulators created BEFORE decorate()
            # (a step taken pre-decorate, or resume via set_state_dict):
            # _accs_for caches per param id, so without this those params
            # would silently never get an f32 master.
            for p in getattr(o, "_parameters", []):
                accs = o._accumulators.get(id(p))
                if accs is not None and "_master" not in accs and \
                        p._value.dtype in (jnp.bfloat16, jnp.float16):
                    accs["_master"] = p._value.astype(jnp.float32)
    return (models, optimizers)


class GradScaler:
    """~ paddle.amp.GradScaler (grad_scaler.py:26): dynamic loss scaling.

    With bf16 (TPU default) scaling is unnecessary; enabled only when
    ``enable=True`` and dtype float16 semantics are requested.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _unscale_and_check(self, optimizer):
        import numpy as np
        found = False
        for p in optimizer._parameters:
            if p._grad is not None:
                g = p._grad._value / self._scale
                p._grad = Tensor(g)
                if not found and not bool(jnp.all(jnp.isfinite(g))):
                    found = True
        self._found_inf = found
        return found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        found = self._unscale_and_check(optimizer)
        if not found:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def state_dict(self):
        return {"scale": self._scale, "incr_count": self._good,
                "decr_count": self._bad}

    def load_state_dict(self, st):
        self._scale = st.get("scale", self._scale)
        self._good = st.get("incr_count", 0)
        self._bad = st.get("decr_count", 0)
