"""Optimizer base + concrete optimizers.

~ python/paddle/optimizer/optimizer.py:50 (accumulator management, _C_ops
fused update kernels) re-expressed functionally: each optimizer defines a
pure ``_update(param, grad, accs, lr) -> (new_param, new_accs)`` rule. The
eager ``step()`` jits one fused update over the whole param pytree (the
analog of the reference's fused/multi_tensor adam paths); the same rule is
reused by jit'ed training loops and by sharded (ZeRO) wrappers which shard
the accumulator pytree over the mesh.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..autograd import no_grad
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


_HOST_MEM_OK = None


def _host_memory_supported() -> bool:
    """Whether the backend exposes pinned host memory for state offload."""
    global _HOST_MEM_OK
    if _HOST_MEM_OK is None:
        try:
            # local_devices: on multi-process runs jax.devices()[0] may be
            # another host's (non-addressable) device and the probe would
            # disable offload inconsistently across ranks
            dev = jax.local_devices()[0]
            kinds = {m.kind for m in dev.addressable_memories()}
            _HOST_MEM_OK = "pinned_host" in kinds
        except Exception:  # noqa: BLE001 — older backends
            _HOST_MEM_OK = False
    return _HOST_MEM_OK


def _global_norm(grads):
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in grads))


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._lr = learning_rate
        self._parameters: List[Parameter] = list(parameters) if parameters else []
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[int, dict] = {}
        self._step_count = 0
        self._jit_update = None
        self._jit_sig = None
        # ~ reference multi_precision: low-precision params keep an f32
        # master copy in the accumulators; the update runs on the master
        # and the param receives its downcast (no bf16 update rounding)
        self._multi_precision = bool(multi_precision)

    # ---- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    # ---- subclass interface ----------------------------------------------
    def _create_accumulators(self, p: Parameter) -> dict:
        return {}

    def _update(self, param, grad, accs, lr, step):
        raise NotImplementedError

    # ---- helpers ----------------------------------------------------------
    def _update_with_master(self, v, g, a, lr, step):
        """Shared wrapper for EVERY update call site (eager fused step,
        sparse rows, static executor): when the accumulators carry an f32
        '_master', the rule runs on the master and the param receives its
        downcast — otherwise plain _update."""
        master = a.get("_master") if isinstance(a, dict) else None
        if master is None:
            return self._update(v, g, a, lr, step)
        rest = {k: x for k, x in a.items() if k != "_master"}
        nm, na = self._update(master, g, rest, lr, step)
        na = dict(na)
        na["_master"] = nm
        return nm.astype(v.dtype), na

    def _accs_for(self, p: Parameter) -> dict:
        if id(p) not in self._accumulators:
            accs = self._create_accumulators(p)
            if self._multi_precision and p._value.dtype in (
                    jnp.bfloat16, jnp.float16):
                accs["_master"] = p._value.astype(jnp.float32)
            self._accumulators[id(p)] = accs
        return self._accumulators[id(p)]

    # ---- ZeRO state sharding (consumer of _shard_states_axis) -------------
    def _zero_mesh(self):
        """Mesh to shard optimizer state over, or None.

        ~ group_sharded_optimizer_stage2.py:48 — the reference segments
        params across ranks by size; here states get NamedShardings over
        the '_shard_states_axis' mesh axis and GSPMD keeps every device's
        addressable shard at 1/N."""
        axis = getattr(self, "_shard_states_axis", None)
        if not axis:
            return None, None
        from ..distributed.topology import get_global_mesh
        mesh = get_global_mesh()
        if mesh is None or axis not in mesh.axis_names \
                or mesh.shape[axis] <= 1:
            return None, None
        return mesh, axis

    def _state_sharding(self, arr, mesh, axis, param_spec=None):
        """Spec for one state array: keep the param's own annotated axes,
        then shard the largest remaining divisible dim over `axis`."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = [None] * arr.ndim
        if param_spec is not None:
            for i, s in enumerate(param_spec[:arr.ndim]):
                if s in mesh.axis_names:
                    spec[i] = s
        if axis not in spec:
            n = mesh.shape[axis]
            for i in sorted(range(arr.ndim), key=lambda i: -arr.shape[i]):
                if spec[i] is None and arr.shape[i] % n == 0 \
                        and arr.shape[i] >= n:
                    spec[i] = axis
                    break
        return NamedSharding(mesh, P(*spec))

    def _ensure_sharded_state(self, params, mesh, axis):
        """Place params (per their annotation; replicated otherwise), grads
        and accumulators onto the mesh. Stage os/os_g: states sharded,
        params replicated. Stage p_g_os: params carry a 'sharding'
        annotation too (group_sharded_stage3.py:58's param segmentation)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        for p in params:
            pspec = getattr(p, "sharding_spec", None)
            if pspec is not None:
                fixed = [s if s in mesh.axis_names else None for s in pspec]
                n = mesh.shape[axis]
                for i, s in enumerate(fixed):
                    if s == axis and p._value.shape[i] % n != 0:
                        fixed[i] = None  # indivisible: keep replicated
                tgt = NamedSharding(mesh, P(*fixed))
            else:
                tgt = NamedSharding(mesh, P())
            if p._value.sharding != tgt:
                p._value = jax.device_put(p._value, tgt)
            if p._grad is not None and p._grad._value.sharding != tgt:
                p._grad._value = jax.device_put(p._grad._value, tgt)
            accs = self._accs_for(p)
            offload = bool(getattr(self, "_offload_states", False)) \
                and _host_memory_supported()
            for k, a in accs.items():
                if not hasattr(a, "ndim"):
                    continue
                sh = self._state_sharding(a, mesh, axis, pspec)
                if offload:
                    # ZeRO-offload (~ group_sharded stage2/3 offload=True):
                    # accumulators live in pinned host memory between
                    # steps; step() moves them to device memory before the
                    # jitted update and back after it (transfers stay
                    # outside jit — see the staging block in step())
                    sh = sh.with_memory_kind("pinned_host")
                if a.sharding != sh:
                    accs[k] = jax.device_put(a, sh)

    def _apply_grad_clip(self, params, grads):
        from ..nn import (ClipGradByGlobalNorm, ClipGradByNorm,
                          ClipGradByValue)
        clip = self._grad_clip
        if clip is None:
            return grads
        if isinstance(clip, ClipGradByValue):
            return [jnp.clip(g, clip.min, clip.max) for g in grads]
        if isinstance(clip, ClipGradByNorm):
            out = []
            for g in grads:
                n = jnp.linalg.norm(g.astype(jnp.float32))
                scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
                out.append((g * scale).astype(g.dtype))
            return out
        if isinstance(clip, ClipGradByGlobalNorm):
            gn = _global_norm(grads)
            scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(gn, 1e-12))
            return [(g * scale).astype(g.dtype) for g in grads]
        return grads

    # ---- main entry -------------------------------------------------------
    @no_grad()
    def step(self):
        from ..core.selected_rows import SelectedRows
        all_params = [p for p in self._parameters
                      if p.trainable and p._grad is not None]
        sparse_params = [p for p in all_params
                         if isinstance(p._grad, SelectedRows)]
        params = [p for p in all_params if not isinstance(p._grad,
                                                          SelectedRows)]
        # ClipGradByGlobalNorm must see ONE norm over dense + sparse grads
        # (reference merges SelectedRows into the global norm); per-tensor
        # clips stay per-group.
        from ..nn import ClipGradByGlobalNorm
        joint_scale = None
        if sparse_params and params and \
                isinstance(self._grad_clip, ClipGradByGlobalNorm):
            merged = [p._grad.merge() for p in sparse_params]
            sq = sum(jnp.sum(jnp.square(p._grad._value.astype(jnp.float32)))
                     for p in params)
            sq = sq + sum(jnp.sum(jnp.square(sr.values.astype(jnp.float32)))
                          for sr in merged)
            gn = jnp.sqrt(sq)
            joint_scale = jnp.minimum(
                1.0, self._grad_clip.clip_norm / jnp.maximum(gn, 1e-12))
        if sparse_params:
            self._sparse_step(sparse_params, scale=joint_scale)
        if not params:
            self._step_count += 1
            return
        mesh, shard_axis = self._zero_mesh()
        if mesh is not None:
            self._ensure_sharded_state(params, mesh, shard_axis)
        grads = [p._grad._value for p in params]
        if joint_scale is not None:
            grads = [(g * joint_scale).astype(g.dtype) for g in grads]
        else:
            grads = self._apply_grad_clip(params, grads)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count + 1, jnp.int32)
        vals = [p._value for p in params]
        accs = [self._accs_for(p) for p in params]

        # ZeRO-offload: host-resident accumulators stream to device memory
        # before the jitted update and back after it (transfers stay
        # OUTSIDE jit — in-jit placement annotations are not supported on
        # every backend). The compute itself always sees device memory.
        acc_host_sh = [
            {k: a[k].sharding
             for k in a
             if getattr(getattr(a[k], "sharding", None), "memory_kind",
                        None) == "pinned_host"}
            for a in accs]
        offload = any(acc_host_sh)
        # Donation safety: only the freshly-staged device copies of the
        # host-pinned entries are private to this step; every other
        # accumulator entry is a LIVE array (aliased by state_dict()
        # snapshots / set_state_dict inputs) whose buffer must survive. So
        # the staged entries travel in their own jit argument, which is the
        # only one donated — without donation the jit would hold old+new
        # offloaded state (2x HBM), defeating offload.
        staged = [
            {k: jax.device_put(a[k], hs[k].with_memory_kind("device"))
             for k in hs}
            for a, hs in zip(accs, acc_host_sh)]
        live = [{k: x for k, x in a.items() if k not in hs}
                for a, hs in zip(accs, acc_host_sh)]

        def fused(vals, grads, staged, live, lr, step):
            new_vals, new_accs = [], []
            for v, g, s, a in zip(vals, grads, staged, live):
                nv, na = self._update_with_master(
                    v, g.astype(jnp.float32), dict(a, **s), lr, step)
                new_vals.append(nv)
                new_accs.append(na)
            return new_vals, new_accs

        # The cached jit bakes in the donation decision AND (on the mesh
        # path) out_shardings over the accumulator pytree — recreate it when
        # either the offload condition or the accumulator structure changes
        # (e.g. amp.decorate(level='O2') retrofitting '_master' keys after a
        # step has already compiled the update).
        jit_sig = (offload, len(vals),
                   tuple(tuple(sorted(a)) for a in accs))
        if self._jit_update is None or self._jit_sig != jit_sig:
            donate = (2,) if offload else ()
            if mesh is not None:
                # pin output shardings so updated params/states stay laid
                # out as placed by _ensure_sharded_state (ZeRO invariant);
                # offloaded accumulators exit in device memory and are
                # moved back to host below
                out_sh = ([v.sharding for v in vals],
                          [dict({k: a[k].sharding for k in a},
                                **{k: s[k].sharding for k in s})
                           for a, s in zip(live, staged)])
                self._jit_update = jax.jit(fused, out_shardings=out_sh,
                                           donate_argnums=donate)
            else:
                self._jit_update = jax.jit(fused, donate_argnums=donate)
            self._jit_sig = jit_sig
        new_vals, new_accs = self._jit_update(vals, grads, staged, live,
                                              lr, step)
        for p, nv, na, hs in zip(params, new_vals, new_accs, acc_host_sh):
            p._value = nv
            if hs:
                na = {k: (jax.device_put(x, hs[k]) if k in hs else x)
                      for k, x in na.items()}
            self._accumulators[id(p)] = na
        self._step_count += 1
        if isinstance(self._lr, LRScheduler) and self._lr._auto_step:
            pass  # paddle semantics: user calls scheduler.step()

    def _sparse_step(self, sparse_params, scale=None):
        """Lazy row-wise update for SelectedRows grads (~ the reference's
        selected_rows optimizer kernels, phi/kernels/selected_rows/
        adam_kernel.h with lazy_mode semantics: only looked-up rows'
        params AND moments advance). ``scale`` is the precomputed joint
        global-norm factor when dense params share the clip."""
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count + 1, jnp.int32)
        for p in sparse_params:
            sr = p._grad.merge()
            rows = sr.rows
            grad_rows = sr.values.astype(jnp.float32)
            if scale is not None:
                grad_rows = grad_rows * scale
            elif self._grad_clip is not None:
                grad_rows = self._apply_grad_clip([p], [grad_rows])[0]
            accs = self._accs_for(p)
            master = accs.get("_master")
            row_keys = [k for k, a in accs.items()
                        if k != "_master"
                        and hasattr(a, "ndim") and a.ndim >= 1
                        and a.shape[:1] == p._value.shape[:1]]
            # multi_precision: the rule runs on the f32 master's rows; the
            # param rows receive the downcast (lazy rows only, like the
            # reference's selected_rows kernels)
            p_rows = (master[rows] if master is not None
                      else p._value[rows].astype(jnp.float32))
            acc_rows = {k: accs[k][rows] for k in row_keys}
            # scalar accumulators (e.g. beta power) pass through untouched
            for k in accs:
                if k not in row_keys and k != "_master":
                    acc_rows[k] = accs[k]
            new_rows, new_accs = self._update(
                p_rows, grad_rows, acc_rows, lr, step)
            if master is not None:
                accs["_master"] = master.at[rows].set(new_rows)
            p._value = p._value.at[rows].set(new_rows.astype(p._value.dtype))
            for k in row_keys:
                accs[k] = accs[k].at[rows].set(new_accs[k])
            for k in new_accs:
                if k not in row_keys and k != "_master":
                    accs[k] = new_accs[k]

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if getattr(loss, "_symbolic", False):
            # static-graph mode: append the update step to the program; the
            # Executor compiles grads+update into the jitted step
            # (~ Optimizer.minimize appending backward + optimize ops)
            from ..static import graph as _sg
            prog = _sg.default_main_program()
            params = parameters or self._parameters or None
            prog._append_opt(self, loss, params)
            pg = _sg.append_backward(loss, params)
            return None, pg
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameters:
            p._grad = None

    clear_gradients = clear_grad

    # ---- state ------------------------------------------------------------
    def state_dict(self) -> dict:
        st = {"step": self._step_count}
        for i, p in enumerate(self._parameters):
            accs = self._accumulators.get(id(p))
            if accs:
                st[f"accs_{i}"] = {k: Tensor(v) for k, v in accs.items()}
        if isinstance(self._lr, LRScheduler):
            st["LR_Scheduler"] = self._lr.state_dict()
        return st

    def set_state_dict(self, st: dict):
        self._step_count = st.get("step", 0)
        for i, p in enumerate(self._parameters):
            key = f"accs_{i}"
            if key in st:
                self._accumulators[id(p)] = {
                    k: (v._value if isinstance(v, Tensor) else jnp.asarray(v))
                    for k, v in st[key].items()}
        if "LR_Scheduler" in st and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(st["LR_Scheduler"])

    @property
    def _learning_rate(self):
        return self._lr


class SGD(Optimizer):
    """~ python/paddle/optimizer/sgd.py over phi sgd kernel."""

    def _update(self, param, grad, accs, lr, step):
        if self._weight_decay:
            grad = grad + self._weight_decay * param.astype(jnp.float32)
        return (param - (lr * grad).astype(param.dtype)), accs


class Momentum(Optimizer):
    """~ python/paddle/optimizer/momentum.py."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_accumulators(self, p):
        return {"velocity": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, param, grad, accs, lr, step):
        if self._weight_decay:
            grad = grad + self._weight_decay * param.astype(jnp.float32)
        v = self._momentum * accs["velocity"] + grad
        if self._nesterov:
            upd = grad + self._momentum * v
        else:
            upd = v
        return (param - (lr * upd).astype(param.dtype)), {"velocity": v}


class Adam(Optimizer):
    """~ python/paddle/optimizer/adam.py over phi adam kernel."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def _create_accumulators(self, p):
        return {"m": jnp.zeros(p._value.shape, jnp.float32),
                "v": jnp.zeros(p._value.shape, jnp.float32)}

    def _decoupled(self):
        return False

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        if self._weight_decay and not self._decoupled():
            grad = grad + self._weight_decay * pf
        b1, b2 = self._beta1, self._beta2
        m = b1 * accs["m"] + (1 - b1) * grad
        v = b2 * accs["v"] + (1 - b2) * jnp.square(grad)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        if self._weight_decay and self._decoupled():
            upd = upd + self._weight_decay * pf
        new_p = pf - lr * upd
        return new_p.astype(param.dtype), {"m": m, "v": v}


class AdamW(Adam):
    """~ python/paddle/optimizer/adamw.py (decoupled decay)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _create_accumulators(self, p):
        return {"m": jnp.zeros(p._value.shape, jnp.float32),
                "u": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        if self._weight_decay:
            grad = grad + self._weight_decay * pf
        m = self._beta1 * accs["m"] + (1 - self._beta1) * grad
        u = jnp.maximum(self._beta2 * accs["u"], jnp.abs(grad))
        t = step.astype(jnp.float32)
        new_p = pf - (lr / (1 - self._beta1 ** t)) * m / (u + self._eps)
        return new_p.astype(param.dtype), {"m": m, "u": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, p):
        return {"moment": jnp.full(p._value.shape, self._init_acc,
                                   jnp.float32)}

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        if self._weight_decay:
            grad = grad + self._weight_decay * pf
        mom = accs["moment"] + jnp.square(grad)
        new_p = pf - lr * grad / (jnp.sqrt(mom) + self._eps)
        return new_p.astype(param.dtype), {"moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, p):
        a = {"mean_square": jnp.zeros(p._value.shape, jnp.float32),
             "momentum": jnp.zeros(p._value.shape, jnp.float32)}
        if self._centered:
            a["mean_grad"] = jnp.zeros(p._value.shape, jnp.float32)
        return a

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        if self._weight_decay:
            grad = grad + self._weight_decay * pf
        ms = self._rho * accs["mean_square"] + (1 - self._rho) * jnp.square(grad)
        new_accs = {"mean_square": ms}
        if self._centered:
            mg = self._rho * accs["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            new_accs["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * accs["momentum"] + lr * grad / denom
        new_accs["momentum"] = mom
        return (pf - mom).astype(param.dtype), new_accs


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon

    def _create_accumulators(self, p):
        return {"avg_sq_grad": jnp.zeros(p._value.shape, jnp.float32),
                "avg_sq_update": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        if self._weight_decay:
            grad = grad + self._weight_decay * pf
        asg = self._rho * accs["avg_sq_grad"] + (1 - self._rho) * jnp.square(grad)
        upd = (jnp.sqrt(accs["avg_sq_update"] + self._eps)
               / jnp.sqrt(asg + self._eps)) * grad
        asu = self._rho * accs["avg_sq_update"] + (1 - self._rho) * jnp.square(upd)
        return (pf - lr * upd).astype(param.dtype), \
            {"avg_sq_grad": asg, "avg_sq_update": asu}


class Lamb(Optimizer):
    """~ python/paddle/optimizer/lamb.py (LAMB trust-ratio scaling)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _create_accumulators(self, p):
        return {"m": jnp.zeros(p._value.shape, jnp.float32),
                "v": jnp.zeros(p._value.shape, jnp.float32)}

    def _update(self, param, grad, accs, lr, step):
        pf = param.astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        m = b1 * accs["m"] + (1 - b1) * grad
        v = b2 * accs["v"] + (1 - b2) * jnp.square(grad)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._weight_decay * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(param.dtype), {"m": m, "v": v}
