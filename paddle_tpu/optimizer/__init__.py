"""paddle_tpu.optimizer. ~ python/paddle/optimizer/__init__.py."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, Optimizer,
    RMSProp, SGD,
)
