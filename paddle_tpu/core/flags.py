"""Typed global flag system.

Equivalent of the reference's exported gflags (paddle/fluid/platform/flags.cc,
surfaced in Python via pybind/global_value_getter_setter.cc and env
``FLAGS_*`` passthrough in python/paddle/fluid/__init__.py __bootstrap__).
One registry, typed defaults, environment override at definition time.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict

_lock = threading.Lock()
_registry: Dict[str, "_Flag"] = {}
# lock-free value mirror for the eager dispatch hot path (GIL-atomic dict
# reads; every write path below keeps it in sync under _lock)
_values: Dict[str, Any] = {}


class _Flag:
    __slots__ = ("name", "value", "type", "help")

    def __init__(self, name: str, value: Any, typ: type, help: str):
        self.name = name
        self.value = value
        self.type = typ
        self.help = help


def _coerce(typ: type, raw: Any) -> Any:
    if typ is bool and isinstance(raw, str):
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


def define_flag(name: str, default: Any, help: str = "", typ: type | None = None) -> None:
    typ = typ or type(default)
    env = os.environ.get(f"FLAGS_{name}")
    value = _coerce(typ, env) if env is not None else default
    with _lock:
        _registry[name] = _Flag(name, value, typ, help)
        _values[name] = value


def get_flags(names=None) -> Dict[str, Any]:
    with _lock:
        if names is None:
            return {k: f.value for k, f in _registry.items()}
        if isinstance(names, str):
            names = [names]
        return {n: _registry[n].value for n in names}


def get_flag(name: str) -> Any:
    # hot path (called per eager op): plain dict read, no lock
    try:
        return _values[name]
    except KeyError:
        with _lock:
            return _registry[name].value


def set_flags(flags: Dict[str, Any]) -> None:
    with _lock:
        for name, value in flags.items():
            if name not in _registry:
                raise KeyError(f"unknown flag {name!r}")
            f = _registry[name]
            f.value = _coerce(f.type, value)
            _values[name] = f.value


# Core flags (subset of platform/flags.cc that is meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for nan/inf after each eager op")
define_flag("benchmark", False, "block-until-ready after each eager op for timing")
define_flag("eager_delete_tensor_gb", 0.0, "kept for API compat; XLA manages memory")
define_flag("use_autotune", True, "enable XLA autotuning knobs where applicable")
define_flag("low_precision_op_list", "", "comma list of ops forced to bf16 under amp")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("context_parallel_backend", "ring",
            "sequence-parallel attention impl: ring (KV ppermute, any head count) | ulysses (two all-to-alls, needs heads % sep == 0)")
define_flag("use_flash_attention", True,
            "use the Pallas flash-attention kernel on eligible shapes; "
            "a kernel failure raises instead of silently degrading")
