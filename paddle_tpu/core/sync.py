"""Device synchronization that is real on every backend.

`jax.block_until_ready` does not guarantee execution has finished on
remote-tunneled platforms (observed on the axon TPU plugin: it returns at
dispatch time, so timings and wait() contracts silently break). A tiny
host readback of one scalar per buffer is the portable barrier — it cannot
complete before the producing computation has.
"""
from __future__ import annotations

import jax


def hard_sync(value) -> None:
    """Block until every array in the pytree is materialized on device.

    Uses block_until_ready first (correct + cheap on local backends), then
    forces a one-element host readback per leaf as the portable barrier.
    """
    leaves = jax.tree_util.tree_leaves(value)
    jax.block_until_ready(leaves)
    for leaf in leaves:
        if (hasattr(leaf, "ravel") and getattr(leaf, "size", 0)
                and getattr(leaf, "is_fully_addressable", True)):
            # multi-host global arrays can't be fetched from one process;
            # block_until_ready above is the best available barrier there
            jax.device_get(jax.numpy.ravel(leaf)[0])


def is_ready(value) -> bool:
    """Non-blocking readiness poll over a pytree (True when unknowable)."""
    for leaf in jax.tree_util.tree_leaves(value):
        probe = getattr(leaf, "is_ready", None)
        if probe is not None and not probe():
            return False
    return True
