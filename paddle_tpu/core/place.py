"""Device / place abstraction.

TPU-native equivalent of the reference's ``phi::Place`` hierarchy
(paddle/phi/common/place.h) and ``paddle.device.set_device``
(python/paddle/device/__init__.py). A Place is a thin view over a
``jax.Device``; there are no streams to manage — XLA owns scheduling.
"""
from __future__ import annotations

import functools
import threading

import jax

_state = threading.local()


class Place:
    """Base place. Mirrors phi::Place (paddle/phi/common/place.h)."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    @property
    def jax_device(self) -> jax.Device:
        devs = _devices_of_type(self.device_type)
        if self.device_id >= len(devs):
            raise ValueError(
                f"device {self.device_type}:{self.device_id} out of range "
                f"({len(devs)} present)")
        return devs[self.device_id]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(TPUPlace):
    """Accelerator-place API-compat alias (~ paddle.CUDAPlace): on this
    framework the accelerator is the TPU, so CUDAPlace(i) denotes device i
    of the default accelerator platform."""


class CUDAPinnedPlace(CPUPlace):
    """~ paddle.CUDAPinnedPlace — host memory; jax manages pinned staging
    buffers itself, so this is the CPU place."""


class NPUPlace(TPUPlace):
    """~ paddle.NPUPlace API-compat alias (custom accelerator slot)."""


class XPUPlace(TPUPlace):
    """~ paddle.XPUPlace API-compat alias."""


@functools.lru_cache(maxsize=None)
def _devices_of_type(kind: str):
    all_devs = jax.devices()
    if kind == "cpu":
        try:
            return jax.devices("cpu")
        except RuntimeError:
            return all_devs
    # treat the default accelerator platform as "tpu" regardless of the
    # backend's self-reported platform string (axon tunnels report 'axon')
    accel = [d for d in all_devs if d.platform != "cpu"]
    return accel or all_devs


def _parse(device: str) -> Place:
    device = device.lower()
    if ":" in device:
        kind, idx = device.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("cpu",):
        return CPUPlace()
    if kind in ("tpu", "xla", "gpu"):  # accept 'gpu' for script compat
        return TPUPlace(idx)
    raise ValueError(f"unknown device {device!r}")


def set_device(device) -> Place:
    """paddle.device.set_device equivalent."""
    place = device if isinstance(device, Place) else _parse(device)
    _state.place = place
    return place


def get_device() -> str:
    p = _current_expected_place()
    return f"{p.device_type}:{p.device_id}"


def _current_expected_place() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        p = TPUPlace(0) if accel else CPUPlace()
        _state.place = p
    return p


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


def device_count() -> int:
    return len(_devices_of_type(_current_expected_place().device_type))
