"""Dtype system.

TPU-native equivalent of the reference's ``phi::DataType`` enum and the
``convert_dtype`` helpers (reference: paddle/phi/common/data_type.h,
python/paddle/fluid/data_feeder.py convert_dtype). We deliberately reuse
numpy/jax dtype objects instead of a parallel enum: XLA is the only backend,
so a wrapper enum would add a translation layer with no benefit.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import dtypes as _jax_dtypes

# Canonical dtype aliases (mirror paddle.float32 etc.)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int": int32,
    "int64": int64, "long": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}

_default_dtype = np.dtype(np.float32)


def set_default_dtype(d) -> None:
    """Mirror of paddle.set_default_dtype (python/paddle/framework/framework.py)."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (np.dtype(np.float16), np.dtype(jnp.bfloat16), np.dtype(np.float32),
                 np.dtype(np.float64)):
        raise TypeError(f"default dtype must be a floating dtype, got {d}")
    _default_dtype = d


def get_default_dtype() -> np.dtype:
    return _default_dtype


def convert_dtype(d) -> np.dtype:
    """Normalize any dtype spec (str / numpy / jax) to a numpy dtype object."""
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        if d not in _STR_ALIASES:
            raise TypeError(f"unsupported dtype string: {d!r}")
        return np.dtype(_STR_ALIASES[d])
    try:
        return np.dtype(d)
    except TypeError as e:
        raise TypeError(f"cannot interpret {d!r} as a dtype") from e


def dtype_name(d) -> str:
    d = convert_dtype(d)
    return d.name


def is_floating_point(d) -> bool:
    d = convert_dtype(d)
    return _jax_dtypes.issubdtype(d, np.inexact)


def is_integer(d) -> bool:
    d = convert_dtype(d)
    return _jax_dtypes.issubdtype(d, np.integer)


def is_bool(d) -> bool:
    return convert_dtype(d) == np.dtype(np.bool_)


def is_complex(d) -> bool:
    d = convert_dtype(d)
    return _jax_dtypes.issubdtype(d, np.complexfloating)


def finfo(d):
    return jnp.finfo(convert_dtype(d))


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))
