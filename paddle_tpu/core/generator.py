"""RNG generator with (seed, offset) semantics.

Equivalent of the reference's phi::Generator (paddle/phi/core/generator.h:23):
per-device generator state = a 64-bit seed plus a monotonically increasing
offset. On TPU this maps naturally onto jax's counter-based PRNG: each random
op consumes ``fold_in(PRNGKey(seed), offset++)`` so results are reproducible
given (seed, offset) and independent across calls — the same contract the
reference's Philox offset gives CUDA kernels.
"""
from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp


class Generator:
    """Stateful RNG source. Mirrors phi::Generator::GetState/SetState/Random64."""

    def __init__(self, seed: int | None = None):
        self._lock = threading.Lock()
        if seed is None:
            seed = int(time.time_ns() % (2**63))
        self._seed = int(seed)
        self._offset = 0

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = int(seed)
            self._offset = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        with self._lock:
            return (self._seed, self._offset)

    def set_state(self, state) -> None:
        with self._lock:
            self._seed, self._offset = int(state[0]), int(state[1])

    def next_key(self) -> jax.Array:
        """Consume one offset tick and return a fresh PRNG key."""
        with self._lock:
            off = self._offset
            self._offset += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), off)

    def split(self, n: int):
        return jax.random.split(self.next_key(), n)


_default_generator = Generator(seed=0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed equivalent (python/paddle/framework/random.py)."""
    return _default_generator.manual_seed(int(s))


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state) -> None:
    _default_generator.set_state(state)


def next_key() -> jax.Array:
    return _default_generator.next_key()
