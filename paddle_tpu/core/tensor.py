"""Eager Tensor.

TPU-native equivalent of the reference's eager ``paddle::experimental::Tensor``
(paddle/phi/api/include/tensor.h) + AutogradMeta (paddle/fluid/eager/
autograd_meta.h): a thin Python object holding an immutable ``jax.Array``
value plus autograd metadata (stop_gradient, grad, producer GradNode).

There is no DenseTensor/storage split here: jax.Array already is the
device-resident, sharding-aware storage (the DenseTensor + Allocation roles),
and XLA owns layout — so the C++-side storage hierarchy collapses to one
field. Mutation APIs (``__setitem__`` etc.) rebind the value functionally.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from ..autograd import tape as _tape


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_grad_node",
                 "_output_index", "_name", "persistable", "__weakref__",
                 "__dict__")

    _next_id = 0

    def __init__(self, value, stop_gradient: bool = True, name: str | None = None):
        # ordered for the dispatch hot path: outputs of eager ops arrive
        # as jax.Array already
        if isinstance(value, jax.Array):
            pass
        elif isinstance(value, Tensor):
            value = value._value
        else:
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._grad_node = None
        self._output_index = 0
        self._name = name  # generated lazily on first access
        self.persistable = False

    @property
    def name(self) -> str:
        n = self._name
        if n is None:
            n = f"tensor_{Tensor._next_id}"
            Tensor._next_id += 1
            self._name = n
        return n

    @name.setter
    def name(self, value):
        self._name = value

    # ---- basic properties -------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def place(self):
        from . import place as _place
        devs = self._value.devices() if hasattr(self._value, "devices") else set()
        d = next(iter(devs)) if devs else None
        if d is None or d.platform == "cpu":
            return _place.CPUPlace()
        return _place.TPUPlace(d.id)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = None if g is None else (g if isinstance(g, Tensor) else Tensor(g))

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    # ---- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _tape.backward([self], [grad_tensor], retain_graph=retain_graph)

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True)
        return t

    def requires_grad_(self, flag: bool = True) -> "Tensor":
        self.stop_gradient = not flag
        return self

    # ---- conversion -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dt) -> "Tensor":
        from ..ops import dispatch as _d
        dt = dtypes.convert_dtype(dt)
        return _d.apply_op("cast", lambda x: x.astype(dt), self)

    cast = astype

    def to(self, *args, **kwargs):
        # minimal: dtype-only or device string
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu") or ":" in str(a):
                from . import place as _place
                p = _place._parse(str(a))
                return Tensor(jax.device_put(self._value, p.jax_device),
                              stop_gradient=self.stop_gradient)
            return self.astype(a)
        return self

    def cpu(self):
        from . import place as _place
        return Tensor(jax.device_put(self._value, _place.CPUPlace().jax_device),
                      stop_gradient=self.stop_gradient)

    def clone(self) -> "Tensor":
        from ..ops import dispatch as _d
        return _d.apply_op("clone", lambda x: x + 0, self)

    def block_until_ready(self) -> "Tensor":
        jax.block_until_ready(self._value)
        return self

    # ---- in-place-style mutation (functional rebind) ----------------------
    def set_value(self, value) -> None:
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {value.shape} vs {self._value.shape}")
        self._value = value

    def copy_(self, other, *a) -> "Tensor":
        self.set_value(other)
        return self

    def fill_(self, v) -> "Tensor":
        self._value = jnp.full_like(self._value, v)
        return self

    def zero_(self) -> "Tensor":
        self._value = jnp.zeros_like(self._value)
        return self

    def scale_(self, s) -> "Tensor":
        self._value = self._value * s
        return self

    def add_(self, other) -> "Tensor":
        self._value = self._value + (other._value if isinstance(other, Tensor) else other)
        return self

    # ---- misc -------------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_flag = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_flag},\n       {np.asarray(self._value)!r})")

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __format__(self, spec):
        return format(self.item() if self.size == 1 else np.asarray(self._value), spec)

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by paddle_tpu.ops.tensor_methods


class Parameter(Tensor):
    """Trainable tensor. ~ paddle.fluid.framework.Parameter / EagerParamBase
    (python/paddle/fluid/framework.py:6574)."""

    def __init__(self, value, trainable: bool = True, name: str | None = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        # optional sharding annotation for GSPMD parallelism
        # (set by paddle_tpu.distributed parallel layers)
        self.sharding_spec = None

    @property
    def trainable(self) -> bool:
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, flag: bool):
        self.stop_gradient = not flag


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor equivalent (python/paddle/tensor/creation.py:77)."""
    if isinstance(data, Tensor):
        val = data._value
    else:
        val = data
    if dtype is not None:
        val = jnp.asarray(val, dtype=dtypes.convert_dtype(dtype))
    else:
        arr = np.asarray(val) if not isinstance(val, jax.Array) else val
        if isinstance(arr, np.ndarray) and arr.dtype == np.float64:
            # follow paddle: python floats default to the default dtype
            val = jnp.asarray(arr, dtype=dtypes.get_default_dtype())
        else:
            val = jnp.asarray(val)
    if place is not None:
        from . import place as _place
        p = place if isinstance(place, _place.Place) else _place._parse(str(place))
        val = jax.device_put(val, p.jax_device)
    return Tensor(val, stop_gradient=stop_gradient)
