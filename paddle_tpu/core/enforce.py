"""Error enforcement utilities.

Equivalent of the reference's PADDLE_ENFORCE macro family
(paddle/phi/core/enforce.h): rich error types with actionable messages.
Python exceptions already carry tracebacks, so this is a thin layer that
standardizes error classes and input validation helpers.
"""
from __future__ import annotations

from . import dtype as _dtype_mod


class EnforceNotMet(RuntimeError):
    """Raised when an internal invariant fails (phi/core/enforce.h)."""


def enforce(cond: bool, msg: str = "enforce failed", *args) -> None:
    if not cond:
        raise EnforceNotMet(msg % args if args else msg)


def check_type(value, name: str, expected_types, op_name: str) -> None:
    if not isinstance(value, expected_types):
        raise TypeError(
            f"{op_name}(): argument '{name}' must be {expected_types}, "
            f"got {type(value).__name__}")


def check_dtype(d, name: str, allowed, op_name: str) -> None:
    d = _dtype_mod.convert_dtype(d)
    allowed_np = [_dtype_mod.convert_dtype(a) for a in allowed]
    if d not in allowed_np:
        raise TypeError(
            f"{op_name}(): argument '{name}' has dtype {d.name}, expected one of "
            f"{[a.name for a in allowed_np]}")


def check_shape_match(a, b, op_name: str) -> None:
    if tuple(a) != tuple(b):
        raise ValueError(f"{op_name}(): shape mismatch {tuple(a)} vs {tuple(b)}")
