"""SelectedRows: sparse row-set gradients.

~ paddle/phi/core/selected_rows.h: a (rows, values, height) triple
standing in for a mostly-zero dense tensor whose only non-zero rows are
``rows`` — the gradient type of sparse embedding lookups, consumed by the
optimizers' lazy row-wise update kernels
(phi/kernels/selected_rows/adam_kernel.h). TPU-native: rows/values are
jax arrays; merge/dense conversion are segment ops XLA lowers to
scatter-adds.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class SelectedRows:
    """height x values.shape[1:] virtual tensor, non-zero on `rows`."""

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        self.values = jnp.asarray(values)
        assert self.values.shape[0] == self.rows.shape[0], \
            (self.values.shape, self.rows.shape)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self) -> "SelectedRows":
        """Sum duplicate rows (~ scatter_op MergeAdd,
        phi/kernels/funcs/selected_rows_functor.h). Eager-path op: row ids
        are concrete, so unique runs host-side and the result has exactly
        the distinct rows — no padding entries that would make moment-
        carrying optimizers touch rows they shouldn't."""
        import numpy as np
        uniq, inv = np.unique(np.asarray(self.rows), return_inverse=True)
        summed = jax.ops.segment_sum(self.values,
                                     jnp.asarray(inv, jnp.int32),
                                     num_segments=len(uniq))
        return SelectedRows(jnp.asarray(uniq, jnp.int32), summed,
                            self.height)

    def to_dense(self) -> jnp.ndarray:
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    # -- arithmetic (leaf grad accumulation) -------------------------------
    def __add__(self, other):
        if isinstance(other, SelectedRows):
            assert other.height == self.height
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        # sparse + dense -> dense
        return self.to_dense() + other

    def __radd__(self, other):
        return self.__add__(other)

    def numpy(self):
        import numpy as np
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"row_dim={self.values.shape[1:]})")
