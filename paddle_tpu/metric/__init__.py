"""Metrics.

~ python/paddle/metric/metrics.py:37 — Metric base + Accuracy/Precision/
Recall/Auc, numpy-accumulated on host (metric state is tiny; keeping it off
device avoids blocking the async dispatch stream).
"""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric(abc.ABC):
    """~ paddle.metric.Metric (metrics.py:37)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """top-k accuracy (metrics.py:184)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] > 1:
            label = label.argmax(-1)
        label = label.reshape(label.shape[0], -1)
        idx = np.argsort(-pred, axis=-1)[:, :self.maxk]
        correct = (idx == label[:, :1]).astype(np.float32)
        return correct

    def update(self, correct):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[:, :k].sum()
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
            accs.append(num / correct.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """binary precision (metrics.py:307)."""

    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """binary recall (metrics.py:407)."""

    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold buckets (metrics.py:505)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos = preds[:, 1]
        else:
            pos = preds.reshape(-1)
        idx = np.clip((pos * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            auc += n * tot_pos + p * n / 2.0
            tot_pos += p
            tot_neg += n
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """functional top-k accuracy (paddle.metric.accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1, 1)
    idx = np.argsort(-pred, axis=-1)[:, :k]
    correct = (idx == lab).any(axis=1).mean()
    return Tensor(np.asarray(correct, dtype=np.float32))
