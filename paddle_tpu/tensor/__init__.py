"""paddle.tensor namespace: re-export the functional op surface.

~ python/paddle/tensor/__init__.py.
"""
from ..ops.creation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.reduction import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.linalg import *  # noqa: F401,F403
from ..ops.activation import *  # noqa: F401,F403
from ..ops.array_ops import *  # noqa: F401,F403
from ..core.tensor import Tensor, to_tensor  # noqa: F401
