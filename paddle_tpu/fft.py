"""FFT ops. ~ python/paddle/fft.py over phi fft kernels (CUFFT in the
reference; XLA's FFT HLO here)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import def_op


def _norm(norm):
    return norm if norm in ("backward", "ortho", "forward") else "backward"


@def_op("fft")
def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("ifft")
def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("fft2")
def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@def_op("ifft2")
def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@def_op("fftn")
def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=_norm(norm))


@def_op("ifftn")
def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=_norm(norm))


@def_op("rfft")
def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("irfft")
def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("rfft2")
def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@def_op("irfft2")
def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@def_op("hfft")
def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("ihfft")
def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=_norm(norm))


@def_op("fftshift")
def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@def_op("ifftshift")
def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


@def_op("fftfreq", nondiff=True)
def fftfreq(n, d=1.0):
    return jnp.fft.fftfreq(int(n), d=d)


@def_op("rfftfreq", nondiff=True)
def rfftfreq(n, d=1.0):
    return jnp.fft.rfftfreq(int(n), d=d)


@def_op("rfftn")
def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=_norm(norm))


@def_op("irfftn")
def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=_norm(norm))


@def_op("hfft2")
def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    # hermitian fft over the last axis after a forward fft on the rest
    # (matches scipy.fft.hfft2: hfftn == fftn over leading axes + hfft last)
    out = jnp.fft.fftn(x, s=None if s is None else s[:-1], axes=axes[:-1],
                       norm=_norm(norm))
    return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=axes[-1],
                        norm=_norm(norm))


@def_op("ihfft2")
def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    out = jnp.fft.ihfft(x, n=None if s is None else s[-1], axis=axes[-1],
                        norm=_norm(norm))
    return jnp.fft.ifftn(out, s=None if s is None else s[:-1], axes=axes[:-1],
                         norm=_norm(norm))


@def_op("hfftn")
def hfftn(x, s=None, axes=None, norm="backward"):
    ax = tuple(range(-x.ndim, 0)) if axes is None else tuple(axes)
    out = jnp.fft.fftn(x, s=None if s is None else s[:-1], axes=ax[:-1],
                       norm=_norm(norm))
    return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=ax[-1],
                        norm=_norm(norm))


@def_op("ihfftn")
def ihfftn(x, s=None, axes=None, norm="backward"):
    ax = tuple(range(-x.ndim, 0)) if axes is None else tuple(axes)
    out = jnp.fft.ihfft(x, n=None if s is None else s[-1], axis=ax[-1],
                        norm=_norm(norm))
    return jnp.fft.ifftn(out, s=None if s is None else s[:-1], axes=ax[:-1],
                         norm=_norm(norm))
