"""paddle.linalg namespace. ~ python/paddle/linalg.py re-exports."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, inv, inverse, lstsq, lu, lu_unpack, matmul, matrix_power,
    matrix_rank, mv, norm, pinv, qr, slogdet, solve, svd, triangular_solve,
)

multi_dot = None


def _multi_dot(tensors):
    out = tensors[0]
    for t in tensors[1:]:
        out = matmul(out, t)
    return out


multi_dot = _multi_dot
