"""Activation layers. ~ python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from ...core.tensor import Parameter
from .. import functional as F
from .. import initializer as init
from .layers import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kw = dict(fixed)
            # capture common scalar args by position
            names = list(_arg_names.get(fn_name, []))
            for n, v in zip(names, args):
                self._kw[n] = v
            for k, v in kwargs.items():
                if k != "name":
                    self._kw[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kw)
    _Act.__name__ = fn_name.title().replace("_", "")
    return _Act


_arg_names = {
    "leaky_relu": ["negative_slope"],
    "elu": ["alpha"],
    "celu": ["alpha"],
    "gelu": ["approximate"],
    "hardtanh": ["min", "max"],
    "hardshrink": ["threshold"],
    "softshrink": ["threshold"],
    "thresholded_relu": ["threshold"],
    "softmax": ["axis"],
    "log_softmax": ["axis"],
    "maxout": ["groups", "axis"],
    "glu": ["axis"],
}

ReLU = _simple("relu")
ReLU6 = _simple("relu6")
LeakyReLU = _simple("leaky_relu")
ELU = _simple("elu")
CELU = _simple("celu")
SELU = _simple("selu")
GELU = _simple("gelu")
Sigmoid = _simple("sigmoid")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Hardtanh = _simple("hardtanh")
Hardshrink = _simple("hardshrink")
Softshrink = _simple("softshrink")
Tanhshrink = _simple("tanhshrink")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Softplus = _simple("softplus")
Softsign = _simple("softsign")
ThresholdedReLU = _simple("thresholded_relu")
LogSigmoid = _simple("log_sigmoid")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
Maxout = _simple("maxout")
GLU = _simple("glu")
Tanh = _simple("tanh")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init_value=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=init.Constant(init_value))
        self.data_format = data_format

    def forward(self, x):
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        fmt = self.data_format

        def fn(xv, wv):
            if wv.shape[0] == 1:
                w = wv.reshape(())
            else:
                shape = [1] * xv.ndim
                ax = 1 if fmt.startswith("NC") else xv.ndim - 1
                shape[ax] = wv.shape[0]
                w = wv.reshape(shape)
            return jnp.where(xv >= 0, xv, w * xv)
        return apply_op("prelu", fn, x, self.weight)
