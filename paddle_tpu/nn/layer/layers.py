"""nn.Layer base class.

~ python/paddle/fluid/dygraph/layers.py ``Layer``: parameter/buffer/sublayer
registration via __setattr__, forward pre/post hooks, state_dict/
set_state_dict, train/eval mode, apply/to. The TPU-specific addition is
``tree_flatten_params`` which exports parameters as a pytree for jit'ed
functional training steps.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core import dtype as _dt
from ...core.tensor import Parameter, Tensor


class HookRemoveHelper:
    """~ fluid/dygraph/layers.py HookRemoveHelper."""

    _next_id = 0

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: str | None = None, dtype="float32"):
        self.training = True
        self._dtype = _dt.convert_dtype(dtype)
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- registration -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                elif isinstance(value, Tensor):
                    params[name].set_value(value)
                    return
                else:
                    del params[name]
            if layers is not None and name in layers and value is None:
                del layers[name]
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    if value is None:
                        del buffers[name]
                    else:
                        buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- parameter creation helper (LayerHelper analog) -------------------
    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as init
        dtype = _dt.convert_dtype(dtype) if dtype else self._dtype
        if default_initializer is None:
            default_initializer = (init.Constant(0.0) if is_bias
                                   else init.XavierNormal())
        if attr is not None and getattr(attr, "initializer", None) is not None:
            default_initializer = attr.initializer
        data = default_initializer(shape, dtype)
        p = Parameter(data)
        if attr is not None:
            if getattr(attr, "learning_rate", None) is not None:
                p.optimize_attr = {"learning_rate": attr.learning_rate}
            if getattr(attr, "trainable", True) is False:
                p.trainable = False
        return p

    # ---- iteration --------------------------------------------------------
    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        memo = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in memo:
                memo.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_parameters(sub_prefix):
                    if id(item[1]) not in memo:
                        memo.add(id(item[1]))
                        yield item

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items()
                    if l is not None)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ---- mode / dtype -----------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = _dt.convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(dtype)
            for b in self.buffers():
                if _dt.is_floating_point(b.dtype):
                    b._value = b._value.astype(dtype)
            self._dtype = dtype
            for l in self.sublayers():
                l._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ---- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    # ---- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                v = v if isinstance(v, Tensor) else Tensor(np.asarray(v))
                if tuple(v.shape) != tuple(t.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: checkpoint "
                        f"{v.shape} vs layer {t.shape}")
                t.set_value(v._value.astype(t._value.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ---- functional bridge (TPU-specific) ---------------------------------
    def tree_flatten_params(self):
        """Export (names, values) of all params+persistable buffers as a dict
        pytree usable inside jax.jit. Pairs with ``load_tree``."""
        tree = {name: p._value for name, p in self.state_dict().items()}
        return tree

    def load_tree(self, tree) -> None:
        sd = self.state_dict()
        for name, v in tree.items():
            if name in sd:
                sd[name]._value = v

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            body = repr(layer).split("\n")
            body = "\n  ".join(body)
            lines.append(f"({name}): {body}")
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        inner = "\n  ".join(lines)
        return f"{main}(\n  {inner}\n)"


class LayerList(Layer):
    """~ python/paddle/nn/layer/container.py LayerList."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return self.__class__(list(self._sub_layers.values())[idx])
        if idx < 0:
            idx += len(self)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    """~ python/paddle/nn/layer/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, (tuple, list)) and len(l) == 2:
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    """~ container.py ParameterList."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    """~ python/paddle/nn/layer/container.py LayerDict."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, sublayer):
        self.add_sublayer(key, sublayer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        v = self._sub_layers[key]
        del self._sub_layers[key]
        return v

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for k, v in sublayers:
            self.add_sublayer(k, v)
