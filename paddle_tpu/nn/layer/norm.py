"""Normalization layers. ~ python/paddle/nn/layer/norm.py."""
from __future__ import annotations

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=init.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """Root-mean-square norm — required by the Llama family. The reference
    gained this only in later versions; TPU build carries it natively."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=init.Constant(1.0))

    def forward(self, x):
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        eps = self.epsilon

        def fn(xv, wv):
            dt = xv.dtype
            xf = xv.astype(jnp.float32)
            var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
            out = xf * jnp.reciprocal(jnp.sqrt(var + eps))
            return (out.astype(dt)) * wv
        return apply_op("rms_norm", fn, x, self.weight)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=init.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(init.Constant(0.0)(
            (num_features,), "float32")))
        self.register_buffer("_variance", Tensor(init.Constant(1.0)(
            (num_features,), "float32")))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch-norm stats under data parallelism are synced by running
    the model inside pjit with batch sharding — XLA computes global-mean
    semantics when the reduction spans the sharded axis. This class is kept
    for API parity (~ nn/layer/norm.py SyncBatchNorm) and behaves as
    BatchNorm in eager single-device mode.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # replace BatchNorm sublayers with SyncBatchNorm (API parity)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=init.Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=init.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (~ nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(
            init.Normal(0, 1)((h,), "float32")))
        self.register_buffer("weight_v", Tensor(
            init.Normal(0, 1)((w,), "float32")))

    def forward(self, weight):
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        dim, iters, eps = self.dim, self.power_iters, self.eps
        u0, v0 = self.weight_u._value, self.weight_v._value

        def fn(wv):
            wm = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return wv / sigma
        return apply_op("spectral_norm", fn, weight)
