"""Loss layers. ~ python/paddle/nn/layer/loss.py."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.kw = dict(ignore_index=ignore_index, reduction=reduction,
                       soft_label=soft_label, axis=axis,
                       use_softmax=use_softmax,
                       label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, **self.kw)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, delta=1.0, reduction="mean", name=None):
        super().__init__()
        self.delta = delta
        self.reduction = reduction

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                       reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self.kw)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    """~ paddle.nn.HingeEmbeddingLoss."""

    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class HSigmoidLoss(Layer):
    """~ paddle.nn.HSigmoidLoss (hierarchical sigmoid over a class tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        import numpy as np
        from ...core.tensor import Parameter
        from ...ops import creation
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes - 1 if not is_custom else num_classes
        limit = float(np.sqrt(6.0 / (feature_size + max(1, n_nodes))))
        self.weight = Parameter(
            (creation.uniform([max(1, n_nodes), feature_size],
                              min=-limit, max=limit))._value)
        if bias_attr is not False:
            self.bias = Parameter(creation.zeros([max(1, n_nodes)])._value)
        else:
            self.bias = None

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)
