"""Recurrent layers.

~ python/paddle/nn/layer/rnn.py (RNNCellBase:117, LSTM:1233, GRU, SimpleRNN).
TPU design: the time loop is a single ``lax.scan`` per direction per layer —
one compiled kernel instead of the reference's per-step cuDNN calls; weights
ride in the carry closure so XLA keeps them in VMEM across steps.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op
from .. import initializer as init
from .layers import Layer, LayerList


class RNNCellBase(Layer):
    """~ rnn.py:117."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        B = batch_ref.shape[batch_dim_idx]
        return full([B, self.hidden_size], init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((hidden_size,), attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((hidden_size,), attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,),)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply_op("simple_rnn_cell", fn, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((4 * hidden_size,),
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((4 * hidden_size,),
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def fn(x, hv, cv, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hv @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * cv + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h2, c2 = apply_op("lstm_cell", fn, inputs, h, c, self.weight_ih,
                          self.weight_hh, self.bias_ih, self.bias_hh)
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter((3 * hidden_size,),
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter((3 * hidden_size,),
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,),)

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(inputs)

        def fn(x, hv, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hv @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            return (1 - z) * n + z * hv
        h2 = apply_op("gru_cell", fn, inputs, h, self.weight_ih,
                      self.weight_hh, self.bias_ih, self.bias_hh)
        return h2, h2


class RNN(Layer):
    """Wraps a cell into a sequence runner (~ rnn.py RNN:771).

    The loop runs as a Python loop over time in eager mode; inside
    jit/to_static XLA unrolls or the functional models use lax.scan.
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import stack
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outputs = []
        for t in steps:
            x_t = inputs[:, t] if time_axis == 1 else inputs[t]
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        return stack(outputs, axis=time_axis), states


class BiRNN(Layer):
    """~ rnn.py BiRNN:905."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, s_fw = self.rnn_fw(inputs, s_fw)
        o_bw, s_bw = self.rnn_bw(inputs, s_bw)
        return concat([o_fw, o_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional stack driven by lax.scan per layer."""

    MODE_CELLS = {"RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell,
                  "LSTM": LSTMCell, "GRU": GRUCell}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.state_components = 2 if mode == "LSTM" else 1
        cells = []
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                Cell = self.MODE_CELLS[mode]
                kw = {}
                if mode.startswith("RNN"):
                    kw["activation"] = "tanh" if mode == "RNN_TANH" else "relu"
                cells.append(Cell(in_sz, hidden_size, weight_ih_attr,
                                  weight_hh_attr, bias_ih_attr, bias_hh_attr,
                                  **kw) if mode.startswith("RNN") is False
                             else Cell(in_sz, hidden_size, **kw))
        self.cells = LayerList(cells)
        self._ndir = ndir

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat, stack
        from ...nn import functional as F
        ndir = self._ndir
        x = inputs
        final_h = []
        final_c = []
        for layer in range(self.num_layers):
            outs = []
            for d in range(ndir):
                cell = self.cells[layer * ndir + d]
                rnn = RNN(cell, is_reverse=(d == 1),
                          time_major=self.time_major)
                if initial_states is not None:
                    if self.mode == "LSTM":
                        h0, c0 = initial_states
                        st = (h0[layer * ndir + d], c0[layer * ndir + d])
                    else:
                        st = initial_states[layer * ndir + d]
                else:
                    st = None
                o, s = rnn(x, st)
                outs.append(o)
                if self.mode == "LSTM":
                    final_h.append(s[0])
                    final_c.append(s[1])
                else:
                    final_h.append(s)
            x = outs[0] if ndir == 1 else concat(outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
        h_st = stack(final_h, axis=0)
        if self.mode == "LSTM":
            c_st = stack(final_c, axis=0)
            return x, (h_st, c_st)
        return x, h_st


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class BeamSearchDecoder:
    """~ paddle.nn.BeamSearchDecoder (python/paddle/fluid/layers/rnn.py
    BeamSearchDecoder:792): beam-expanded single-step decoder over an RNN
    cell, driven by :func:`dynamic_decode`."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _merge(self, v):
        return v.reshape((-1,) + v.shape[2:])

    def _split(self, v, batch):
        return v.reshape((batch, self.beam_size) + v.shape[1:])

    def initialize(self, initial_cell_states):
        cs = jax.tree.map(
            lambda t: self.tile_beam_merge_with_batch(t, self.beam_size)._value
            if isinstance(t, Tensor) else t, initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        batch = jax.tree.leaves(initial_cell_states)[0].shape[0]
        ids = jnp.full((batch, self.beam_size), self.start_token, jnp.int64
                       if False else jnp.int32)
        # only beam 0 is live initially so duplicated beams don't tie
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        return ids, (cs, log_probs, finished)

    def step(self, time, inputs, states):
        cell_states, log_probs, finished = states
        batch = log_probs.shape[0]
        inp = Tensor(self._merge(inputs.astype(jnp.int32))) \
            if not isinstance(inputs, Tensor) else inputs
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        wrapped_states = jax.tree.map(
            lambda v: Tensor(v), cell_states,
            is_leaf=lambda v: isinstance(v, jax.Array))
        out, next_states = self.cell(inp, wrapped_states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        logits = out._value  # (batch*beam, vocab)
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        step_lp = step_lp.reshape(batch, self.beam_size, vocab)
        # finished beams only extend with end_token at zero cost
        mask = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], mask[None, None, :], step_lp)
        total = log_probs[..., None] + step_lp
        flat = total.reshape(batch, self.beam_size * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int32)
        token = (top_idx % vocab).astype(jnp.int32)
        new_finished = jnp.take_along_axis(finished, parent, axis=1) \
            | (token == self.end_token)
        gathered_states = jax.tree.map(
            lambda v: self._merge(jnp.take_along_axis(
                self._split(v._value if isinstance(v, Tensor) else v, batch),
                parent.reshape(batch, self.beam_size, *([1] * (v.ndim - 1))),
                axis=1)), next_states,
            is_leaf=lambda v: isinstance(v, (Tensor, jax.Array)))
        return (token, parent), (gathered_states, top_lp, new_finished)

    def finalize(self, tokens, parents):
        # tokens/parents: lists over time of (batch, beam)
        from .. import functional as Fn
        ids = Tensor(jnp.stack(tokens))          # (T, batch, beam)
        par = Tensor(jnp.stack(parents))
        seqs = Fn.gather_tree(ids, par)
        return seqs


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """~ paddle.nn.dynamic_decode (fluid/layers/rnn.py dynamic_decode:1393).

    Eager loop with early exit when every beam is finished; each step is one
    XLA program (cell + top-k), so the hot path stays on-device."""
    ids, states = decoder.initialize(inits)
    tokens, parents = [], []
    inputs = ids
    t = 0
    while t <= int(max_step_num):
        (token, parent), states = decoder.step(t, inputs, states)
        tokens.append(token)
        parents.append(parent)
        inputs = token
        t += 1
        if bool(jnp.all(states[2])):
            break
    seqs = decoder.finalize(tokens, parents)
    if not output_time_major:
        seqs = Tensor(jnp.moveaxis(seqs._value, 0, 1))
    # length per (batch, beam): steps up to and including the first end token
    tb = seqs._value if output_time_major else \
        jnp.moveaxis(seqs._value, 1, 0)          # (T, batch, beam)
    T = tb.shape[0]
    is_end = tb == decoder.end_token
    any_end = jnp.any(is_end, axis=0)
    first_end = jnp.argmax(is_end, axis=0) + 1
    lengths = Tensor(jnp.where(any_end, first_end, T).astype(jnp.int32))
    if return_length:
        return seqs, states, lengths
    return seqs, states
