"""Weight initializers.

~ python/paddle/nn/initializer/ (fluid/initializer.py). Initializers are
callables (shape, dtype) -> jax array, consuming the global Generator.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as _dt
from ...core import generator as _gen


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weights are stored OIHW (matching the reference's layout)
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(int(s) for s in shape), self.value,
                        _dt.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = _dt.convert_dtype(dtype)
        z = jax.random.normal(_gen.next_key(), tuple(int(s) for s in shape),
                              dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = _dt.convert_dtype(dtype)
        z = jax.random.truncated_normal(_gen.next_key(), -2.0, 2.0,
                                        tuple(int(s) for s in shape),
                                        dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dt = _dt.convert_dtype(dtype)
        z = jax.random.uniform(_gen.next_key(), tuple(int(s) for s in shape),
                               minval=self.low, maxval=self.high,
                               dtype=jnp.float32)
        return z.astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dt = _dt.convert_dtype(dtype)
        z = jax.nn.initializers.orthogonal(scale=self.gain)(
            _gen.next_key(), tuple(int(s) for s in shape), jnp.float32)
        return z.astype(dt)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ...core.tensor import Tensor
        v = self.value._value if isinstance(self.value, Tensor) else np.asarray(self.value)
        return jnp.asarray(v, dtype=_dt.convert_dtype(dtype)).reshape(
            tuple(int(s) for s in shape))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        arr = np.zeros(shape, dtype=_dt.convert_dtype(dtype))
        o, i = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for k in range(min(o, i * self.groups)):
            idx = (k, k % i) + tuple(centers)
            arr[idx] = 1.0
        return jnp.asarray(arr)


# lowercase API-compat aliases used in ParamAttr(initializer=...)
constant = Constant
normal = Normal
uniform = Uniform


class ParamAttr:
    """~ paddle.ParamAttr (python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class Bilinear(Initializer):
    """~ paddle.nn.initializer.Bilinear — bilinear-upsampling kernel init for
    transposed conv weights (shape [C_out, C_in, k, k])."""

    def __call__(self, shape, dtype=None):
        dt = _dt.convert_dtype(dtype)
        arr = np.zeros(tuple(int(s) for s in shape), dtype=np.float32)
        if len(shape) < 3:
            return jnp.asarray(arr.astype(dt))
        k = shape[-1]
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        coords = np.arange(k)
        kernel1d = 1 - np.abs(coords / f - c)
        kernel = np.outer(kernel1d, kernel1d) if len(shape) >= 4 else kernel1d
        arr[...] = kernel
        return jnp.asarray(arr.astype(dt))


def calculate_gain(nonlinearity, param=None):
    """~ paddle.nn.initializer.calculate_gain."""
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": float(np.sqrt(2.0)),
        "leaky_relu": float(np.sqrt(2.0 / (1 + (param if param is not None
                                                else 0.01) ** 2))),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """~ paddle.nn.initializer.set_global_initializer: default initializers
    applied by layers that don't specify weight_attr/bias_attr."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init)


def get_global_initializer():
    return _global_initializer
