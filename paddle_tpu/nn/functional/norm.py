"""Normalization functional ops.

~ python/paddle/nn/functional/norm.py over phi batch_norm/layer_norm kernels
(paddle/phi/kernels/batch_norm_kernel.h, layer_norm_kernel.h). On TPU these
are jnp reductions + elementwise that XLA fuses into single passes; layer
norm additionally has a Pallas fused kernel (paddle_tpu/ops/pallas/) used on
the jit path for long rows.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    """~ phi batch_norm; in training mode updates running stats in place
    (functional rebind on the stat tensors, matching paddle's mutable
    mean/variance outputs)."""
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1 \
        if isinstance(x, Tensor) else 1
    nd = x.ndim
    if data_format in ("NHWC", "NLC", "NDHWC"):
        channel_axis = nd - 1
    axes = tuple(i for i in range(nd) if i != channel_axis)
    shape = [1] * nd
    shape[channel_axis] = x.shape[channel_axis]

    use_stats = (not training) if use_global_stats is None else use_global_stats

    if training and not use_stats:
        # compute batch stats and update running stats host-side state.
        # Stats are f32 regardless of compute dtype: bf16 mean/var loses
        # ~3 decimal digits and the running buffers are f32 anyway.
        mean_v = apply_op(
            "bn_mean",
            lambda v: jnp.mean(v.astype(jnp.float32), axis=axes), x)
        var_v = apply_op(
            "bn_var",
            lambda v: jnp.var(v.astype(jnp.float32), axis=axes), x)
        with_stats_x = x
        if running_mean is not None and not getattr(mean_v, "_symbolic",
                                                    False):
            # static-graph capture: batch stats are symbolic, so the running
            # stats stay frozen inside the compiled program. The blend casts
            # back to the buffer's dtype — f32 batch stats must not silently
            # promote a bf16-cast model's buffers.
            running_mean._value = (
                momentum * running_mean._value
                + (1 - momentum) * mean_v._value
            ).astype(running_mean._value.dtype)
            running_var._value = (
                momentum * running_var._value
                + (1 - momentum) * var_v._value
            ).astype(running_var._value.dtype)
        mean_use, var_use = mean_v, var_v
    else:
        mean_use, var_use = running_mean, running_var

    args = [x, mean_use, var_use]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def fn(xv, mv, vv, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        bv = rest[i] if has_b else None
        # normalize in f32 and cast back: with bf16 activations + f32
        # running stats, plain promotion would silently upcast the whole
        # downstream network to f32 (half MXU rate); with bf16 stats the
        # rsqrt loses precision. f32 inside, storage dtype outside.
        xf = xv.astype(jnp.float32)
        inv = jnp.reciprocal(jnp.sqrt(
            vv.astype(jnp.float32).reshape(shape) + epsilon))
        out = (xf - mv.astype(jnp.float32).reshape(shape)) * inv
        if wv is not None:
            out = out * wv.astype(jnp.float32).reshape(shape)
        if bv is not None:
            out = out + bv.astype(jnp.float32).reshape(shape)
        return out.astype(xv.dtype)
    return apply_op("batch_norm", fn, *args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n = len(normalized_shape)
    axes = tuple(range(x.ndim - n, x.ndim))
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def fn(xv, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        bv = rest[i] if has_b else None
        mu = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xv - mu), axis=axes, keepdims=True)
        out = (xv - mu) * jnp.reciprocal(jnp.sqrt(var + epsilon))
        if wv is not None:
            out = out * wv
        if bv is not None:
            out = out + bv
        return out
    return apply_op("layer_norm", fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    nd = x.ndim
    channel_axis = 1 if data_format.startswith("NC") else nd - 1
    axes = tuple(i for i in range(2, nd)) if channel_axis == 1 else \
        tuple(i for i in range(1, nd - 1))
    shape = [1] * nd
    shape[channel_axis] = x.shape[channel_axis]
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def fn(xv, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        bv = rest[i] if has_b else None
        mu = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.var(xv, axis=axes, keepdims=True)
        out = (xv - mu) * jnp.reciprocal(jnp.sqrt(var + eps))
        if wv is not None:
            out = out * wv.reshape(shape)
        if bv is not None:
            out = out + bv.reshape(shape)
        return out
    return apply_op("instance_norm", fn, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW"):
    nd = x.ndim
    channel_last = not data_format.startswith("NC")
    c_ax = nd - 1 if channel_last else 1
    C = x.shape[c_ax]
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def fn(xv, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        bv = rest[i] if has_b else None
        if channel_last:
            xm = jnp.moveaxis(xv, -1, 1)
        else:
            xm = xv
        N = xm.shape[0]
        g = xm.reshape((N, num_groups, C // num_groups) + xm.shape[2:])
        axes = tuple(range(2, g.ndim))
        mu = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        g = (g - mu) * jnp.reciprocal(jnp.sqrt(var + epsilon))
        out = g.reshape(xm.shape)
        shape = (1, C) + (1,) * (xm.ndim - 2)
        if wv is not None:
            out = out * wv.reshape(shape)
        if bv is not None:
            out = out + bv.reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("group_norm", fn, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12):
    def fn(xv):
        norm = jnp.sum(jnp.abs(xv) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return xv / jnp.maximum(norm, epsilon)
    return apply_op("normalize", fn, x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    def fn(xv):
        channel_axis = 1 if data_format.startswith("NC") else xv.ndim - 1
        sq = jnp.square(xv)
        C = xv.shape[channel_axis]
        half = size // 2
        pads = [(0, 0)] * xv.ndim
        pads[channel_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(xv)
        for i in range(size):
            sl = [slice(None)] * xv.ndim
            sl[channel_axis] = slice(i, i + C)
            acc = acc + padded[tuple(sl)]
        return xv / jnp.power(k + alpha * acc, beta)
    return apply_op("local_response_norm", fn, x)
