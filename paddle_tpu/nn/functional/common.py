"""Common functional ops: linear, embedding, dropout, interpolate, etc.

~ python/paddle/nn/functional/common.py + input.py over phi kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import generator as _gen
from ...core.tensor import Tensor
from ...ops.dispatch import apply_op
from ...ops import manipulation as _manip


def linear(x, weight, bias=None):
    """~ phi matmul+add fused (reference fc). weight layout (in, out) to
    match paddle.nn.Linear (python/paddle/nn/layer/common.py:123)."""
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        out = jnp.matmul(xv, wv)
        if rest:
            out = out + rest[0]
        return out
    return apply_op("linear", fn, *args)


def embedding(x, weight, padding_idx=None, sparse=False):
    """~ phi embedding (lookup_table_v2); padding_idx rows get zero grad via
    zeroed output rows.

    sparse=True: the weight gradient is recorded as a SelectedRows
    (rows=looked-up ids, values=output cotangent rows) instead of a dense
    (V, H) scatter — the reference's lookup_table_v2 is_sparse path whose
    grad flows into the optimizers' lazy row-wise updates
    (phi/kernels/selected_rows/).
    """
    from ..._internal_sparse_embed import maybe_sparse_embedding
    out = maybe_sparse_embedding(x, weight, padding_idx, sparse)
    if out is not None:
        return out

    def fn(ids, wv):
        out = jnp.take(wv, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(out.dtype)
            out = out * mask
        return out
    return apply_op("embedding", fn, x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            rng_key=None):
    """~ phi dropout (seed+offset driven, phi/kernels/dropout_kernel.h)."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = rng_key if rng_key is not None else _gen.next_key()

    def fn(xv):
        shape = list(xv.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            for i in range(len(shape)):
                if i not in axes:
                    shape[i] = 1
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, xv / (1.0 - p), 0.0).astype(xv.dtype)
        return jnp.where(keep, xv, 0.0).astype(xv.dtype)
    return apply_op("dropout", fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _gen.next_key()

    def fn(xv):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, xv.shape)
        a = (1.0 / np.sqrt((1 - p) * (1 + p * alpha_p ** 2)))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, xv, alpha_p) + b).astype(xv.dtype)
    return apply_op("alpha_dropout", fn, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    return _manip.pad(x, pad=pad, mode=mode, value=value,
                      data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    """~ phi interpolate family (nearest/bilinear/bicubic/trilinear/area)."""
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    nd = x.ndim
    n_spatial = nd - 2
    in_spatial = (list(x.shape[1:-1]) if channel_last
                  else list(x.shape[2:]))
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_spatial = [int(s) for s in size]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * n_spatial
        out_spatial = [int(np.floor(s * f))
                       for s, f in zip(in_spatial, scale_factor)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic",
             "area": "linear"}[mode]

    def fn(xv):
        if channel_last:
            target = (xv.shape[0],) + tuple(out_spatial) + (xv.shape[-1],)
        else:
            target = (xv.shape[0], xv.shape[1]) + tuple(out_spatial)
        if jmode == "nearest" or not align_corners:
            return jax.image.resize(xv, target, method=jmode).astype(xv.dtype)
        # align_corners path: use explicit gather with corner-aligned coords
        out = xv
        spatial_axes = (list(range(1, 1 + n_spatial)) if channel_last
                        else list(range(2, 2 + n_spatial)))
        for ax, osz in zip(spatial_axes, out_spatial):
            isz = out.shape[ax]
            if osz == 1 or isz == 1:
                idx = jnp.zeros((osz,), jnp.float32)
            else:
                idx = jnp.linspace(0.0, isz - 1.0, osz)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, isz - 1)
            w = (idx - lo).astype(out.dtype)
            shp = [1] * out.ndim
            shp[ax] = osz
            w = w.reshape(shp)
            out = (jnp.take(out, lo, axis=ax) * (1 - w)
                   + jnp.take(out, hi, axis=ax) * w)
        return out.astype(xv.dtype)
    return apply_op("interpolate", fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col ~ phi unfold."""
    def _t(v):
        return (int(v), int(v)) if isinstance(v, int) else tuple(int(a) for a in v)
    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    if isinstance(paddings, int):
        ph0 = ph1 = pw0 = pw1 = paddings
    elif len(paddings) == 2:
        ph0 = ph1 = paddings[0]
        pw0 = pw1 = paddings[1]
    else:
        ph0, pw0, ph1, pw1 = paddings

    def fn(xv):
        N, C, H, W = xv.shape
        xp = jnp.pad(xv, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)])
        oh = (H + ph0 + ph1 - (dh * (kh - 1) + 1)) // sh + 1
        ow = (W + pw0 + pw1 - (dw * (kw - 1) + 1)) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = xp[:, :, i * dh:i * dh + (oh - 1) * sh + 1:sh,
                        j * dw:j * dw + (ow - 1) * sw + 1:sw]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # N, C, kh*kw, oh, ow
        return out.reshape(N, C * kh * kw, oh * ow)
    return apply_op("unfold", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    def _t(v):
        return (int(v), int(v)) if isinstance(v, int) else tuple(int(a) for a in v)
    oh, ow = _t(output_sizes)
    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    p = _t(paddings) if not isinstance(paddings, int) else (paddings, paddings)
    ph, pw = p[0], p[1]

    def fn(xv):
        N = xv.shape[0]
        C = xv.shape[1] // (kh * kw)
        lh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        lw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        cols = xv.reshape(N, C, kh, kw, lh, lw)
        out = jnp.zeros((N, C, oh + 2 * ph, ow + 2 * pw), xv.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + (lh - 1) * sh + 1:sh,
                             j * dw:j * dw + (lw - 1) * sw + 1:sw].add(
                    cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return apply_op("fold", fn, x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)

    def fn(xv):
        if data_format == "NCHW":
            N, C, H, W = xv.shape
            out = xv.reshape(N, C // (r * r), r, r, H, W)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(N, C // (r * r), H * r, W * r)
        N, H, W, C = xv.shape
        out = xv.reshape(N, H, W, r, r, C // (r * r))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(N, H * r, W * r, C // (r * r))
    return apply_op("pixel_shuffle", fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)

    def fn(xv):
        N, C, H, W = xv.shape
        out = xv.reshape(N, C, H // r, r, W // r, r)
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        return out.reshape(N, C * r * r, H // r, W // r)
    return apply_op("pixel_unshuffle", fn, x)


def channel_shuffle(x, groups, data_format="NCHW"):
    g = int(groups)

    def fn(xv):
        N, C, H, W = xv.shape
        out = xv.reshape(N, g, C // g, H, W)
        out = jnp.swapaxes(out, 1, 2)
        return out.reshape(N, C, H, W)
    return apply_op("channel_shuffle", fn, x)


def bilinear(x1, x2, weight, bias=None):
    args = [x1, x2, weight] + ([bias] if bias is not None else [])

    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    return apply_op("bilinear", fn, *args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply_op("cosine_similarity", fn, x1, x2)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    def fn(lv):
        n = lv.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._value if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * lv + epsilon * pd
        return (1 - epsilon) * lv + epsilon / n
    return apply_op("label_smooth", fn, label)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    def fn(lv):
        m = maxlen if maxlen is not None else int(jnp.max(lv))
        mask = jnp.arange(m)[None, :] < lv.reshape(-1, 1)
        return mask.astype(jnp.dtype(dtype)).reshape(lv.shape + (m,))
    return apply_op("sequence_mask", fn, lengths, nondiff=True)


def one_hot(x, num_classes, name=None):
    """~ paddle.nn.functional.one_hot (phi one_hot kernel)."""
    return apply_op("one_hot",
                    lambda v: jax.nn.one_hot(v, num_classes,
                                             dtype=jnp.float32), x)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """~ paddle.nn.functional.diag_embed: batch of diagonal matrices from the
    last dim of ``input`` placed at (dim1, dim2) of the output."""
    def fn(v):
        n = v.shape[-1]
        size = n + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (size, size), dtype=v.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        # diagonal currently occupies the last two axes; move them into place
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        return jnp.transpose(out, perm)
    return apply_op("diag_embed", fn, input)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """~ paddle.nn.functional.zeropad2d — pad = [left, right, top, bottom]."""
    l, r, t, b = [int(p) for p in padding]

    def fn(v):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (t, b), (l, r)]
        else:
            cfg = [(0, 0), (t, b), (l, r), (0, 0)]
        return jnp.pad(v, cfg)
    return apply_op("zeropad2d", fn, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    """~ paddle.nn.functional.class_center_sample
    (operators/class_center_sample_op.cu): sample the positive class centers
    plus random negatives; returns (remapped_label, sampled_class_index).
    Data-dependent output order -> host-side op (the reference's kernel also
    materializes the unique set)."""
    from ...core.generator import default_generator
    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    n_extra = max(0, num_samples - pos.size)
    if n_extra > 0:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        key = default_generator().next_key()
        perm = np.asarray(jax.random.permutation(key, rest.size))
        sampled = np.concatenate([pos, rest[perm[:n_extra]]])
    else:
        sampled = pos
    remap = -np.ones(num_classes, dtype=lab.dtype)
    remap[sampled] = np.arange(sampled.size)
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled.astype(np.int64 if lab.dtype.kind == "i"
                                              else lab.dtype))))


def gather_tree(ids, parents):
    """~ paddle.nn.functional.gather_tree (phi gather_tree kernel): walk
    beam-search parent pointers backwards to assemble full predicted
    sequences. ids/parents: (max_time, batch, beam)."""
    def fn(idv, parv):
        T = idv.shape[0]

        def step(carry, t):
            beams = carry  # (batch, beam) current beam index per slot
            out_t = jnp.take_along_axis(idv[t], beams, axis=1)
            par_t = jnp.take_along_axis(parv[t], beams, axis=1)
            return par_t, out_t

        init = jnp.broadcast_to(jnp.arange(idv.shape[2], dtype=idv.dtype),
                                idv.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]
    return apply_op("gather_tree", fn, ids, parents, nondiff=True)
