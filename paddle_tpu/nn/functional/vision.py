"""Vision-oriented functional ops.

~ python/paddle/nn/functional/vision.py (affine_grid, grid_sample,
pixel_shuffle) + extension.py (temporal_shift) over phi affine_grid /
grid_sample kernels. Gather-heavy ops that XLA lowers to fused dynamic
gathers; all shapes static so they tile cleanly on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op


def _affine_grid(theta, out_shape, align_corners):
    n, c, h, w = [int(s) for s in out_shape]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
    # theta: (N, 2, 3); grid = base @ theta^T -> (N, H, W, 2)
    return jnp.einsum("hwk,njk->nhwj", base, theta.astype(jnp.float32)) \
        .astype(theta.dtype)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """~ paddle.nn.functional.affine_grid."""
    if hasattr(out_shape, "tolist"):
        out_shape = out_shape.tolist()
    return apply_op("affine_grid",
                    lambda t: _affine_grid(t, out_shape, align_corners),
                    theta)


def _reflect(x, lo, hi):
    # reflect coordinates into [lo, hi] (inclusive range semantics)
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    dbl = 2 * rng
    x = jnp.mod(jnp.abs(x - lo), dbl)
    return lo + jnp.where(x > rng, dbl - x, x)


def _grid_sample(x, grid, mode, padding_mode, align_corners):
    # x: (N, C, H, W); grid: (N, Ho, Wo, 2) in [-1, 1] (x, y) order
    N, C, H, W = x.shape
    gx = grid[..., 0].astype(jnp.float32)
    gy = grid[..., 1].astype(jnp.float32)

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) / 2.0 * (size - 1)
        return ((g + 1.0) * size - 1.0) / 2.0

    fx = unnorm(gx, W)
    fy = unnorm(gy, H)

    if padding_mode == "border":
        fx = jnp.clip(fx, 0, W - 1)
        fy = jnp.clip(fy, 0, H - 1)
    elif padding_mode == "reflection":
        if align_corners:
            fx = _reflect(fx, 0.0, W - 1.0)
            fy = _reflect(fy, 0.0, H - 1.0)
        else:
            fx = jnp.clip(_reflect(fx, -0.5, W - 0.5), 0, W - 1)
            fy = jnp.clip(_reflect(fy, -0.5, H - 0.5), 0, H - 1)

    def gather(iy, ix):
        iyc = jnp.clip(iy, 0, H - 1)
        ixc = jnp.clip(ix, 0, W - 1)
        # (N, C, Ho, Wo) gather per batch
        out = x[jnp.arange(N)[:, None, None], :, iyc, ixc]  # (N,Ho,Wo,C)
        out = jnp.moveaxis(out, -1, 1)
        if padding_mode == "zeros":
            valid = ((iy >= 0) & (iy <= H - 1) & (ix >= 0)
                     & (ix <= W - 1)).astype(x.dtype)
            out = out * valid[:, None, :, :]
        return out

    if mode == "nearest":
        ix = jnp.round(fx).astype(jnp.int32)
        iy = jnp.round(fy).astype(jnp.int32)
        return gather(iy, ix)

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1 = x0 + 1
    y1 = y0 + 1
    wx = (fx - x0.astype(jnp.float32)).astype(x.dtype)
    wy = (fy - y0.astype(jnp.float32)).astype(x.dtype)
    v00 = gather(y0, x0)
    v01 = gather(y0, x1)
    v10 = gather(y1, x0)
    v11 = gather(y1, x1)
    wxe = wx[:, None]
    wye = wy[:, None]
    top = v00 * (1 - wxe) + v01 * wxe
    bot = v10 * (1 - wxe) + v11 * wxe
    return top * (1 - wye) + bot * wye


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """~ paddle.nn.functional.grid_sample (phi grid_sample kernel)."""
    return apply_op("grid_sample",
                    lambda v, g: _grid_sample(v, g, mode, padding_mode,
                                              align_corners), x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """~ paddle.nn.functional.temporal_shift (TSM op,
    paddle/phi/kernels/temporal_shift_kernel.h): shift a leading fraction of
    channels one step back/forward along the segment (time) axis."""
    def fn(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        nt, c, h, w = v.shape
        n = nt // seg_num
        r = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate(
            [r[:, 1:, :c1], jnp.zeros_like(r[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(r[:, :1, c1:c2]), r[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, r[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("temporal_shift", fn, x)
