"""Loss functional ops.

~ python/paddle/nn/functional/loss.py over phi cross_entropy/bce/... kernels
(paddle/phi/kernels/cross_entropy_kernel.h etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """~ phi softmax_with_cross_entropy (fused log-softmax + nll)."""
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(logits, lab, *rest):
        wv = rest[0] if rest else None
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            if wv is not None:
                loss = loss * jnp.sum(soft * wv, axis=axis)
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim:
            lab_i = jnp.squeeze(lab_i, axis=axis)
        valid = (lab_i != ignore_index)
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            logp, safe[..., None], axis=-1 if axis in (-1, logp.ndim - 1)
            else axis).squeeze(axis)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=axis)
            nll = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
        else:
            nll = -picked
        nll = jnp.where(valid, nll, 0.0)
        if wv is not None:
            w = jnp.take(wv, safe)
            w = jnp.where(valid, w, 0.0)
            nll = nll * w
            if reduction == "mean":
                return jnp.sum(nll) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
            return jnp.sum(nll) / denom
        return _reduce(nll, reduction)
    return apply_op("cross_entropy", fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = apply_op("unsqueeze_loss",
                    lambda v: jnp.expand_dims(v, axis), loss)
    if return_softmax:
        from ...ops.activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    return cross_entropy(input, label, weight=weight,
                         ignore_index=ignore_index, reduction=reduction,
                         use_softmax=False)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    return apply_op("binary_cross_entropy", fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    args = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        args.append(weight)
    if has_pw:
        args.append(pos_weight)

    def fn(z, y, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        pw = rest[i] if has_pw else None
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            logsig = -jax.nn.softplus(-z)
            log1msig = -z - logsig if False else -jax.nn.softplus(z)
            base = -(pw * y * logsig + (1 - y) * log1msig)
        if wv is not None:
            base = base * wv
        return _reduce(base, reduction)
    return apply_op("bce_with_logits", fn, *args)


def mse_loss(input, label, reduction="mean"):
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def l1_loss(input, label, reduction="mean"):
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op("huber_loss", fn, input, label)


def kl_div(input, label, reduction="mean"):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def fn(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return apply_op("margin_ranking_loss", fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def fn(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op("triplet_margin_loss", fn, input, positive, negative)


def log_loss(input, label, epsilon=1e-4):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon)
                 + (1 - y) * jnp.log(1 - p + epsilon))
    return apply_op("log_loss", fn, input, label)


def square_error_cost(input, label):
    return apply_op("square_error_cost",
                    lambda a, b: jnp.square(a - b), input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax (jax-native alpha recursion)."""
    import optax

    def fn(lp, lab):
        # optax expects (B, T, C) logits, paddle gives (T, B, C)
        logits = jnp.transpose(lp, (1, 0, 2))
        B, T, C = logits.shape
        ilen = input_lengths._value if isinstance(input_lengths, Tensor) \
            else jnp.asarray(input_lengths)
        llen = label_lengths._value if isinstance(label_lengths, Tensor) \
            else jnp.asarray(label_lengths)
        logit_pad = (jnp.arange(T)[None, :] >= ilen[:, None]).astype(jnp.float32)
        lab_pad = (jnp.arange(lab.shape[1])[None, :]
                   >= llen[:, None]).astype(jnp.float32)
        loss = optax.ctc_loss(logits, logit_pad, lab, lab_pad,
                              blank_id=blank)
        if norm_by_times:
            loss = loss / jnp.maximum(ilen.astype(loss.dtype), 1.0)
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", fn, log_probs, labels)
