"""Loss functional ops.

~ python/paddle/nn/functional/loss.py over phi cross_entropy/bce/... kernels
(paddle/phi/kernels/cross_entropy_kernel.h etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops.dispatch import apply_op


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """~ phi softmax_with_cross_entropy (fused log-softmax + nll)."""
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(logits, lab, *rest):
        wv = rest[0] if rest else None
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            if wv is not None:
                loss = loss * jnp.sum(soft * wv, axis=axis)
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim:
            lab_i = jnp.squeeze(lab_i, axis=axis)
        valid = (lab_i != ignore_index)
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            logp, safe[..., None], axis=-1 if axis in (-1, logp.ndim - 1)
            else axis).squeeze(axis)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=axis)
            nll = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
        else:
            nll = -picked
        nll = jnp.where(valid, nll, 0.0)
        if wv is not None:
            w = jnp.take(wv, safe)
            w = jnp.where(valid, w, 0.0)
            nll = nll * w
            if reduction == "mean":
                return jnp.sum(nll) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
            return jnp.sum(nll) / denom
        return _reduce(nll, reduction)
    return apply_op("cross_entropy", fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    loss = apply_op("unsqueeze_loss",
                    lambda v: jnp.expand_dims(v, axis), loss)
    if return_softmax:
        from ...ops.activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    return cross_entropy(input, label, weight=weight,
                         ignore_index=ignore_index, reduction=reduction,
                         use_softmax=False)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    return apply_op("binary_cross_entropy", fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    args = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        args.append(weight)
    if has_pw:
        args.append(pos_weight)

    def fn(z, y, *rest):
        i = 0
        wv = rest[i] if has_w else None
        i += has_w
        pw = rest[i] if has_pw else None
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            logsig = -jax.nn.softplus(-z)
            log1msig = -z - logsig if False else -jax.nn.softplus(z)
            base = -(pw * y * logsig + (1 - y) * log1msig)
        if wv is not None:
            base = base * wv
        return _reduce(base, reduction)
    return apply_op("bce_with_logits", fn, *args)


def mse_loss(input, label, reduction="mean"):
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def l1_loss(input, label, reduction="mean"):
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op("huber_loss", fn, input, label)


def kl_div(input, label, reduction="mean"):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def fn(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return apply_op("margin_ranking_loss", fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def fn(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op("triplet_margin_loss", fn, input, positive, negative)


def log_loss(input, label, epsilon=1e-4):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon)
                 + (1 - y) * jnp.log(1 - p + epsilon))
    return apply_op("log_loss", fn, input, label)


def square_error_cost(input, label):
    return apply_op("square_error_cost",
                    lambda a, b: jnp.square(a - b), input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax (jax-native alpha recursion)."""
    import optax

    def fn(lp, lab):
        # optax expects (B, T, C) logits, paddle gives (T, B, C)
        logits = jnp.transpose(lp, (1, 0, 2))
        B, T, C = logits.shape
        ilen = input_lengths._value if isinstance(input_lengths, Tensor) \
            else jnp.asarray(input_lengths)
        llen = label_lengths._value if isinstance(label_lengths, Tensor) \
            else jnp.asarray(label_lengths)
        logit_pad = (jnp.arange(T)[None, :] >= ilen[:, None]).astype(jnp.float32)
        lab_pad = (jnp.arange(lab.shape[1])[None, :]
                   >= llen[:, None]).astype(jnp.float32)
        loss = optax.ctc_loss(logits, logit_pad, lab, lab_pad,
                              blank_id=blank)
        if norm_by_times:
            loss = loss / jnp.maximum(ilen.astype(loss.dtype), 1.0)
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", fn, log_probs, labels)


def dice_loss(input, label, epsilon=1e-5):
    """~ paddle.nn.functional.dice_loss (python/paddle/nn/functional/loss.py):
    1 - 2|X∩Y| / (|X|+|Y|) over the flattened per-sample maps; label is
    integer class ids one-hotted against the channel dim."""
    def fn(x, lab):
        nclass = x.shape[-1]
        lab = jax.nn.one_hot(jnp.squeeze(lab, -1), nclass, dtype=x.dtype)
        reduce_dims = tuple(range(1, x.ndim))
        inter = 2.0 * jnp.sum(x * lab, axis=reduce_dims)
        union = jnp.sum(x, axis=reduce_dims) + jnp.sum(lab, axis=reduce_dims)
        return jnp.mean(1.0 - (inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", fn, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """~ paddle.nn.functional.npair_loss — improved N-pair metric loss."""
    def fn(a, p, lab):
        lab = jnp.reshape(lab.astype(a.dtype), (-1, 1))
        same = (lab == lab.T).astype(a.dtype)
        target = same / jnp.sum(same, axis=1, keepdims=True)
        logits = a @ p.T
        ce = jnp.mean(
            jnp.sum(-target * jax.nn.log_softmax(logits, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg
    return apply_op("npair_loss", fn, anchor, positive, labels)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    """~ paddle.nn.functional.sigmoid_focal_loss (RetinaNet focal loss)."""
    def fn(x, y, *rest):
        y = y.astype(x.dtype)
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            loss = loss * (alpha * y + (1 - alpha) * (1 - y))
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply_op("sigmoid_focal_loss", fn, *args)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """~ paddle.nn.functional.hsigmoid_loss (phi hsigmoid_loss kernel).

    Default complete-binary-tree hierarchy: class c's path is the binary
    expansion of c + num_classes (the leaf's heap index); inner nodes are
    rows of ``weight``. Custom trees come in via path_table/path_code."""
    def fn(x, lab, w, *rest):
        b = rest[0] if bias is not None else None
        depth = max(1, int(np.ceil(np.log2(max(2, num_classes)))))
        lab = lab.reshape(-1)
        if path_table is not None:
            pt = path_table._value if hasattr(path_table, "_value") \
                else jnp.asarray(path_table)
            pc = path_code._value if hasattr(path_code, "_value") \
                else jnp.asarray(path_code)
            nodes = pt[lab]
            codes = pc[lab].astype(x.dtype)
            valid = (nodes >= 0).astype(x.dtype)
            nodes = jnp.maximum(nodes, 0)
        else:
            heap = lab + num_classes
            levels = []
            codes_l = []
            h = heap
            for _ in range(depth):
                codes_l.append((h % 2).astype(x.dtype))
                h = h // 2
                levels.append(h)
            nodes = jnp.stack(levels[::-1], axis=1) - 1  # inner nodes, 0-based
            codes = jnp.stack(codes_l[::-1], axis=1)
            valid = (nodes >= 0) & (nodes < w.shape[0])
            valid = valid.astype(x.dtype)
            nodes = jnp.clip(nodes, 0, w.shape[0] - 1)
        wsel = w[nodes]                      # (B, D, feat)
        logits = jnp.einsum("bdf,bf->bd", wsel, x)
        if b is not None:
            logits = logits + b.reshape(-1)[nodes]
        # code 1 -> right branch: loss = softplus(-sign*logit), sign=+1 left
        sign = 1.0 - 2.0 * codes
        z = sign * logits
        loss = jnp.maximum(-z, 0) + jnp.log1p(jnp.exp(-jnp.abs(z)))
        loss = jnp.sum(loss * valid, axis=1, keepdims=True)
        return loss
    args = (input, label, weight) + ((bias,) if bias is not None else ())
    return apply_op("hsigmoid_loss", fn, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """~ paddle.nn.functional.margin_cross_entropy
    (operators/margin_cross_entropy_op.cu): ArcFace-family margin softmax
    cos(m1*theta + m2) - m3 applied to the target logit. The reference's
    model-parallel class split (group) maps to a sharded class dim under
    pjit; single-group math here."""
    def fn(x, lab):
        lab = lab.reshape(-1)
        theta = jnp.arccos(jnp.clip(x, -1.0, 1.0))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lab, x.shape[-1], dtype=x.dtype)
        adj = jnp.where(onehot > 0, tgt, x) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
        if reduction == "mean":
            loss_r = jnp.mean(loss)
        elif reduction == "sum":
            loss_r = jnp.sum(loss)
        else:
            loss_r = loss
        if return_softmax:
            return loss_r, jax.nn.softmax(adj, axis=-1)
        return loss_r
    return apply_op("margin_cross_entropy", fn, logits, label)
