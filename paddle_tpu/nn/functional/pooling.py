"""Pooling functional ops.

~ python/paddle/nn/functional/pooling.py over phi pool kernels
(paddle/phi/kernels/pool_kernel.h). Lowered to lax.reduce_window.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op


def _tuplize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pool_nd(x, kind, kernel_size, stride, padding, n, data_format,
             ceil_mode=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ks = _tuplize(kernel_size, n)
    st = _tuplize(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tuplize(padding, n)
        pad = [(pi, pi) for pi in p]
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
    if isinstance(pad, str):
        pads = pad
    elif channel_last:
        pads = [(0, 0)] + pad + [(0, 0)]
    else:
        pads = [(0, 0), (0, 0)] + pad

    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                    pads)
        return out
    # avg pool
    ones = jnp.ones_like(x)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and (isinstance(pads, str) and pads == "SAME"
                      or isinstance(pads, list) and any(p != (0, 0) for p in pads)):
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads)
        return s / cnt
    return s / float(np.prod(ks))


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    return apply_op("max_pool1d",
                    lambda v: _pool_nd(v, "max", kernel_size, stride, padding,
                                       1, data_format, ceil_mode), x)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    return apply_op("max_pool2d",
                    lambda v: _pool_nd(v, "max", kernel_size, stride, padding,
                                       2, data_format, ceil_mode), x)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    return apply_op("max_pool3d",
                    lambda v: _pool_nd(v, "max", kernel_size, stride, padding,
                                       3, data_format, ceil_mode), x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return apply_op("avg_pool1d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       1, data_format, ceil_mode, exclusive), x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    return apply_op("avg_pool2d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       2, data_format, ceil_mode, exclusive), x)


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    return apply_op("avg_pool3d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       3, data_format, ceil_mode, exclusive), x)


def _adaptive_pool(x, output_size, n, kind, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sz = _tuplize(output_size, n)
    spatial_axes = list(range(1, n + 1)) if channel_last else \
        list(range(2, n + 2))
    # adaptive = reduce_window with computed kernel when divisible, else
    # bucketed mean via reshape when divisible; general case: interpolate bins
    out = x
    for ax, osz in zip(spatial_axes, out_sz):
        isz = out.shape[ax]
        if osz == 1:
            out = (jnp.max if kind == "max" else jnp.mean)(out, axis=ax,
                                                          keepdims=True)
        elif isz % osz == 0:
            k = isz // osz
            new_shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1:]
            r = jnp.reshape(out, new_shape)
            out = (jnp.max if kind == "max" else jnp.mean)(r, axis=ax + 1)
        else:
            # general bins (start/end like paddle's adaptive pooling)
            starts = (np.arange(osz) * isz) // osz
            ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
            slices = []
            for s, e in zip(starts, ends):
                sl = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                slices.append((jnp.max if kind == "max" else jnp.mean)(
                    sl, axis=ax, keepdims=True))
            out = jnp.concatenate(slices, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return apply_op("adaptive_avg_pool1d",
                    lambda v: _adaptive_pool(v, output_size, 1, "avg",
                                             data_format), x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return apply_op("adaptive_avg_pool2d",
                    lambda v: _adaptive_pool(v, output_size, 2, "avg",
                                             data_format), x)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return apply_op("adaptive_avg_pool3d",
                    lambda v: _adaptive_pool(v, output_size, 3, "avg",
                                             data_format), x)


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    return apply_op("adaptive_max_pool1d",
                    lambda v: _adaptive_pool(v, output_size, 1, "max",
                                             data_format), x)


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return apply_op("adaptive_max_pool2d",
                    lambda v: _adaptive_pool(v, output_size, 2, "max",
                                             data_format), x)


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    return apply_op("adaptive_max_pool3d",
                    lambda v: _adaptive_pool(v, output_size, 3, "max",
                                             data_format), x)
