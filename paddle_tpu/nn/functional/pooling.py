"""Pooling functional ops.

~ python/paddle/nn/functional/pooling.py over phi pool kernels
(paddle/phi/kernels/pool_kernel.h). Lowered to lax.reduce_window.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op


def _tuplize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pool_nd(x, kind, kernel_size, stride, padding, n, data_format,
             ceil_mode=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ks = _tuplize(kernel_size, n)
    st = _tuplize(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tuplize(padding, n)
        pad = [(pi, pi) for pi in p]
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
    if isinstance(pad, str):
        pads = pad
    elif channel_last:
        pads = [(0, 0)] + pad + [(0, 0)]
    else:
        pads = [(0, 0), (0, 0)] + pad

    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                    pads)
        return out
    # avg pool
    ones = jnp.ones_like(x)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and (isinstance(pads, str) and pads == "SAME"
                      or isinstance(pads, list) and any(p != (0, 0) for p in pads)):
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads)
        return s / cnt
    return s / float(np.prod(ks))


def _max_pool_indices(x, ks, st, pad, n):
    """Argmax indices into the flattened input spatial map (paddle's
    return_mask contract: int32 index into prod(spatial) per window), NC*
    layout. Built from conv_general_dilated_patches so stride/padding follow
    the exact same windowing as the pooling reduce_window."""
    spatial = x.shape[2:]
    if isinstance(pad, str):
        pads = jax.lax.padtype_to_pads(spatial, ks, st, pad)
    else:
        pads = list(pad)
    full_pads = [(0, 0), (0, 0)] + list(pads)
    # conv_general_dilated_patches zero-pads, but the value path pads with
    # -inf; pad manually so the argmax never selects a padded element, then
    # extract with VALID. The pad value is the finite dtype minimum, not
    # -inf: patch extraction is a conv with a one-hot kernel and -inf * 0 =
    # nan would poison every pad-adjacent window. Real elements are nudged
    # strictly above the pad value so a pad slot can never win the argmax,
    # even for all--inf windows.
    if jnp.issubdtype(x.dtype, jnp.floating):
        neg = jnp.array(jnp.finfo(x.dtype).min, x.dtype)
        xv = jnp.maximum(x, jnp.finfo(x.dtype).min / 2)
    else:
        neg = jnp.array(jnp.iinfo(x.dtype).min, x.dtype)
        xv = jnp.maximum(x, jnp.iinfo(x.dtype).min + 1)
    xpad = jnp.pad(xv, full_pads, constant_values=neg)
    xp = jax.lax.conv_general_dilated_patches(
        xpad, filter_shape=ks, window_strides=st, padding="VALID")
    # xp: (N, C*prod(ks), *out_spatial); reshape to (N, C, prod(ks), ...)
    out_spatial = xp.shape[2:]
    k = int(np.prod(ks))
    xp = xp.reshape(x.shape[0], x.shape[1], k, *out_spatial)
    arg = jnp.argmax(xp, axis=2)  # within-window offset, row-major over ks
    # exact integer linear index: window origin + within-window offset per
    # dim (no float index grid — float32 can't represent indices > 2^24)
    rem = arg
    offs = [None] * n
    for d in range(n - 1, -1, -1):
        offs[d] = rem % ks[d]
        rem = rem // ks[d]
    lin = None
    for d in range(n):
        shape = [1] * arg.ndim
        shape[2 + d] = out_spatial[d]
        start = (jnp.arange(out_spatial[d]) * st[d]
                 - pads[d][0]).reshape(shape)
        coord = jnp.clip(start + offs[d], 0, spatial[d] - 1)
        lin = coord if lin is None else lin * spatial[d] + coord
    return lin.astype(jnp.int32)


def _max_pool_nd(x, kernel_size, stride, padding, n, data_format, ceil_mode,
                 return_mask):
    out = _pool_nd(x, "max", kernel_size, stride, padding, n, data_format,
                   ceil_mode)
    if not return_mask:
        return out
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    v = jnp.moveaxis(x, -1, 1) if channel_last else x
    ks = _tuplize(kernel_size, n)
    st = _tuplize(stride if stride is not None else kernel_size, n)
    pad = padding.upper() if isinstance(padding, str) else \
        [(pi, pi) for pi in _tuplize(padding, n)]
    idx = _max_pool_indices(v, ks, st, pad, n)
    if channel_last:
        idx = jnp.moveaxis(idx, 1, -1)
    return out, idx


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    return apply_op("max_pool1d",
                    lambda v: _max_pool_nd(v, kernel_size, stride, padding,
                                           1, data_format, ceil_mode,
                                           return_mask), x)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    return apply_op("max_pool2d",
                    lambda v: _max_pool_nd(v, kernel_size, stride, padding,
                                           2, data_format, ceil_mode,
                                           return_mask), x)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    return apply_op("max_pool3d",
                    lambda v: _max_pool_nd(v, kernel_size, stride, padding,
                                           3, data_format, ceil_mode,
                                           return_mask), x)


def _max_unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                   n, data_format):
    """Scatter pooled values back to their argmax positions.

    ~ phi max_unpool kernels (paddle/phi/kernels/unpool_kernel.h): indices
    address the flattened spatial block of the *output* map."""
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    v = jnp.moveaxis(x, -1, 1) if channel_last else x
    idx = jnp.moveaxis(indices, -1, 1) if channel_last else indices
    ks = _tuplize(kernel_size, n)
    st = _tuplize(stride if stride is not None else kernel_size, n)
    p = _tuplize(padding, n)
    in_spatial = v.shape[2:]
    if output_size is None:
        out_spatial = tuple(
            (in_spatial[i] - 1) * st[i] - 2 * p[i] + ks[i] for i in range(n))
    else:
        out_spatial = tuple(int(s) for s in output_size[-n:])
    N, C = v.shape[0], v.shape[1]
    flat_len = int(np.prod(out_spatial))
    vals = v.reshape(N, C, -1)
    flat_idx = idx.reshape(N, C, -1)
    out = jnp.zeros((N, C, flat_len), dtype=v.dtype)
    n_idx = jnp.arange(N)[:, None, None]
    c_idx = jnp.arange(C)[None, :, None]
    out = out.at[n_idx, c_idx, flat_idx].set(vals)
    out = out.reshape((N, C) + out_spatial)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return apply_op("max_unpool1d",
                    lambda v, i: _max_unpool_nd(v, i, kernel_size, stride,
                                                padding, output_size, 1,
                                                data_format), x, indices)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return apply_op("max_unpool2d",
                    lambda v, i: _max_unpool_nd(v, i, kernel_size, stride,
                                                padding, output_size, 2,
                                                data_format), x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return apply_op("max_unpool3d",
                    lambda v, i: _max_unpool_nd(v, i, kernel_size, stride,
                                                padding, output_size, 3,
                                                data_format), x, indices)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return apply_op("avg_pool1d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       1, data_format, ceil_mode, exclusive), x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    return apply_op("avg_pool2d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       2, data_format, ceil_mode, exclusive), x)


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    return apply_op("avg_pool3d",
                    lambda v: _pool_nd(v, "avg", kernel_size, stride, padding,
                                       3, data_format, ceil_mode, exclusive), x)


def _adaptive_pool(x, output_size, n, kind, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    out_sz = _tuplize(output_size, n)
    spatial_axes = list(range(1, n + 1)) if channel_last else \
        list(range(2, n + 2))
    # adaptive = reduce_window with computed kernel when divisible, else
    # bucketed mean via reshape when divisible; general case: interpolate bins
    out = x
    for ax, osz in zip(spatial_axes, out_sz):
        isz = out.shape[ax]
        if osz == 1:
            out = (jnp.max if kind == "max" else jnp.mean)(out, axis=ax,
                                                          keepdims=True)
        elif isz % osz == 0:
            k = isz // osz
            new_shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1:]
            r = jnp.reshape(out, new_shape)
            out = (jnp.max if kind == "max" else jnp.mean)(r, axis=ax + 1)
        else:
            # general bins (start/end like paddle's adaptive pooling)
            starts = (np.arange(osz) * isz) // osz
            ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
            slices = []
            for s, e in zip(starts, ends):
                sl = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                slices.append((jnp.max if kind == "max" else jnp.mean)(
                    sl, axis=ax, keepdims=True))
            out = jnp.concatenate(slices, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return apply_op("adaptive_avg_pool1d",
                    lambda v: _adaptive_pool(v, output_size, 1, "avg",
                                             data_format), x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return apply_op("adaptive_avg_pool2d",
                    lambda v: _adaptive_pool(v, output_size, 2, "avg",
                                             data_format), x)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return apply_op("adaptive_avg_pool3d",
                    lambda v: _adaptive_pool(v, output_size, 3, "avg",
                                             data_format), x)


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    return apply_op("adaptive_max_pool1d",
                    lambda v: _adaptive_pool(v, output_size, 1, "max",
                                             data_format), x)


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return apply_op("adaptive_max_pool2d",
                    lambda v: _adaptive_pool(v, output_size, 2, "max",
                                             data_format), x)


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    return apply_op("adaptive_max_pool3d",
                    lambda v: _adaptive_pool(v, output_size, 3, "max",
                                             data_format), x)
