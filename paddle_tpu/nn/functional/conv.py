"""Convolution functional ops.

~ python/paddle/nn/functional/conv.py over phi conv kernels
(paddle/phi/kernels/conv_kernel.h, gpudnn/conv_kernel.cu). Lowered to
lax.conv_general_dilated — XLA maps these onto the MXU directly, playing the
role cuDNN algo selection plays in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.dispatch import apply_op


def _tuplize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _norm_padding(padding, n, strides=None):
    """Return (lax padding, jax 'SAME'/'VALID' or explicit list)."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]]
    if len(padding) == n + 2:
        return [tuple(int(x) for x in p) for p in padding[2:]]
    raise ValueError(f"bad padding {padding}")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    spatial = "DHW"[3 - n:] if n <= 3 else None
    if channel_last:
        lhs_spec = "N" + spatial + "C"
        out_spec = lhs_spec
    else:
        lhs_spec = "NC" + spatial
        out_spec = lhs_spec
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, rhs_spec, out_spec))
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=_tuplize(stride, n),
        padding=_norm_padding(padding, n),
        rhs_dilation=_tuplize(dilation, n),
        dimension_numbers=dn,
        feature_group_count=int(groups))
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    fmt = "NLC" if data_format == "NLC" else "NCL"
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_nd(xv, wv, bv, stride, padding, dilation, groups, 1, fmt)
    return apply_op("conv1d", fn, *args)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_nd(xv, wv, bv, stride, padding, dilation, groups, 2,
                        data_format)
    return apply_op("conv2d", fn, *args)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_nd(xv, wv, bv, stride, padding, dilation, groups, 3,
                        data_format)
    return apply_op("conv3d", fn, *args)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle stores transpose-conv weight as (in, out/groups, *k); running the
    # equivalent fractionally-strided forward conv means: treat dim0 as the
    # conv's input channels (spec "IO...") and flip the kernel spatially
    # (the explicit form of lax's old transpose_kernel flag).
    rhs_spec = "IO" + spatial
    spatial_axes = tuple(range(2, 2 + n))
    pad = _norm_padding(padding, n)
    strides = _tuplize(stride, n)
    dils = _tuplize(dilation, n)
    if isinstance(pad, str):
        if pad == "SAME":
            pad = [( (dils[i] * (weight.shape[2 + i] - 1)) // 2,
                     (dils[i] * (weight.shape[2 + i] - 1) + 1) // 2)
                   for i in range(n)]
        else:
            pad = [(0, 0)] * n
    # grad-of-conv padding: k_eff - 1 - p
    ksp = weight.shape[2:]
    pad_cfg = []
    out_pad = _tuplize(output_padding, n)
    for i in range(n):
        k_eff = (ksp[i] - 1) * dils[i] + 1
        lo = k_eff - 1 - pad[i][0]
        hi = k_eff - 1 - pad[i][1] + out_pad[i]
        pad_cfg.append((lo, hi))

    def one_group(a, w):
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape,
                                            (lhs_spec, rhs_spec, lhs_spec))
        return jax.lax.conv_general_dilated(
            a, jnp.flip(w, spatial_axes), window_strides=(1,) * n,
            padding=pad_cfg, lhs_dilation=strides, rhs_dilation=dils,
            dimension_numbers=dn)

    if groups != 1:
        xi = jnp.split(x, groups, axis=-1 if channel_last else 1)
        wi = jnp.split(weight, groups, axis=0)
        outs = [one_group(a, w) for a, w in zip(xi, wi)]
        out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
    else:
        out = one_group(x, weight)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_transpose_nd(xv, wv, bv, stride, padding, output_padding,
                                  dilation, groups, 1, data_format)
    return apply_op("conv1d_transpose", fn, *args)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_transpose_nd(xv, wv, bv, stride, padding, output_padding,
                                  dilation, groups, 2, data_format)
    return apply_op("conv2d_transpose", fn, *args)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    args = [x, weight] + ([bias] if bias is not None else [])

    def fn(xv, wv, *rest):
        bv = rest[0] if rest else None
        return _conv_transpose_nd(xv, wv, bv, stride, padding, output_padding,
                                  dilation, groups, 3, data_format)
    return apply_op("conv3d_transpose", fn, *args)
