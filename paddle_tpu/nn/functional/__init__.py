"""nn.functional namespace.

~ python/paddle/nn/functional/__init__.py.
"""
from ...ops.activation import (  # noqa: F401
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, selu, sigmoid, silu, softmax, softplus, softshrink, softsign,
    swish, tanhshrink, thresholded_relu,
)
from ...ops.math import tanh  # noqa: F401
from .common import (  # noqa: F401
    alpha_dropout, bilinear, channel_shuffle, cosine_similarity, dropout,
    dropout2d, dropout3d, embedding, fold, interpolate, label_smooth, linear,
    pad, pixel_shuffle, pixel_unshuffle, sequence_mask, unfold, upsample,
)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose,
)
from .loss import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits,
    cosine_embedding_loss, cross_entropy, ctc_loss, hinge_embedding_loss,
    huber_loss, kl_div, l1_loss, log_loss, margin_ranking_loss, mse_loss,
    nll_loss, smooth_l1_loss, softmax_with_cross_entropy, square_error_cost,
    triplet_margin_loss,
)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d, avg_pool1d,
    avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
)
from .attention import scaled_dot_product_attention  # noqa: F401
from .pooling import (  # noqa: F401
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .loss import (  # noqa: F401
    dice_loss, hsigmoid_loss, margin_cross_entropy, npair_loss,
    sigmoid_focal_loss,
)
from .common import (  # noqa: F401
    class_center_sample, diag_embed, gather_tree, one_hot, zeropad2d,
)
from .vision import (  # noqa: F401
    affine_grid, grid_sample, temporal_shift,
)
from .attention import block_sparse_attention, sparse_attention  # noqa: F401


def _make_inplace_act(fn):
    def wrapper(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._value = out._value
        return x
    wrapper.__name__ = fn.__name__ + "_"
    return wrapper


relu_ = _make_inplace_act(relu)
elu_ = _make_inplace_act(elu)
tanh_ = _make_inplace_act(tanh)
softmax_ = _make_inplace_act(softmax)
