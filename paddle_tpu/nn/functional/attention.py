"""Attention functional ops.

The reference's fused attention lives in CUDA
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h — plain
O(s^2) attention). Here the eager path is jnp (XLA fuses well already); the
jit/perf path swaps in the Pallas flash-attention kernel from
paddle_tpu.ops.pallas when shapes qualify (see ops/pallas/flash_attention.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply_op


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, use_pallas="auto"):
    """query/key/value: (batch, seq, heads, head_dim) — paddle convention.

    Routes to the Pallas flash-attention kernel under jit when available and
    shapes are TPU-tile friendly; otherwise the XLA softmax composition.
    """
    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])

    def fn(q, k, v, *rest):
        mask = rest[0] if rest else None
        use_flash = use_pallas is True
        if use_flash and mask is not None:
            # the flash kernel has no mask input; silently running unmasked
            # (or silently falling back to the dense path the caller
            # explicitly opted out of) would both be wrong
            raise ValueError(
                "scaled_dot_product_attention(use_pallas=True) does not "
                "support attn_mask; use is_causal or use_pallas='auto'")
        if use_pallas == "auto":
            # flash kernel needs seq multiples of block size and no custom
            # mask — eligibility is decided HERE, up front, so any error
            # out of the kernel/wrapper below (shard_map spec mismatches,
            # tracing failures, Mosaic rejections) propagates instead of
            # silently degrading to the dense path (repo-wide no-silent-
            # fallback policy, matching the llama flash path).
            use_flash = (mask is None and q.shape[1] >= 256
                         and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
                         and q.shape[-1] in (64, 128, 256))
        if use_flash:
            from ...ops.autotune import tuned_flash_attention
            from ...parallel.pallas_sharding import shard_map_attention
            # GSPMD can't partition a Pallas call: the shared wrapper
            # runs the kernel shard_mapped over auto 'model'/'data'
            # axes so Q/K/V aren't all-gathered around it
            out = shard_map_attention(
                lambda a, b, c: tuned_flash_attention(
                    a, b, c, causal=is_causal),
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2))
            return out.swapaxes(1, 2)
        scale = 1.0 / math.sqrt(q.shape[-1])
        # (b, s, h, d) -> (b, h, s, d)
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        if is_causal:
            sq, sk = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
        if mask is not None:
            if mask.dtype == jnp.bool_:
                scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
            else:
                scores = scores + mask
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
        return jnp.swapaxes(out, 1, 2)
    return apply_op("scaled_dot_product_attention", fn, *args)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """~ paddle.nn.functional.sparse_attention
    (operators/sparse_attention_op.cu, block-sparse SDD attention).

    TPU lowering: the CSR pattern (offset/columns per head) is expanded to an
    attention mask with static-nnz scatter (searchsorted over the offset
    vector gives each nonzero's row), then one fused masked softmax-matmul —
    XLA tiles it on the MXU. True block-sparsity (masked blocks SKIPPED,
    not computed) is ``block_sparse_attention`` below over the Pallas
    splash kernel (ops/pallas/splash_attention.py)."""
    import numpy as np

    def fn(q, k, v, off, cols):
        B, H, L, D = q.shape
        nnz = cols.shape[-1]
        scale = 1.0 / np.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        neg = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)
        pos = jnp.arange(nnz)
        # rows[i] = which row the i-th nonzero belongs to (CSR expansion)
        def expand(off_h, cols_h):
            rows = jnp.searchsorted(off_h, pos, side="right") - 1
            m = jnp.zeros((L, L), dtype=bool).at[rows, cols_h].set(True)
            return m
        mask = jax.vmap(jax.vmap(expand))(
            jnp.broadcast_to(off, (B, H) + off.shape[-1:]),
            jnp.broadcast_to(cols, (B, H, nnz)))
        scores = jnp.where(mask, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(mask, probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return apply_op("sparse_attention", fn, query, key, value,
                    sparse_csr_offset, sparse_csr_columns)


def block_sparse_attention(query, key, value, block_mask, is_causal=False,
                           block_q=None, block_k=None):
    """Block-sparse flash attention over a static (nq, nk) bool block
    pattern — masked-out blocks are skipped entirely (compute scales with
    density). query/key/value: (batch, seq, heads, head_dim) paddle
    layout; ``block_mask`` a numpy bool array tiling the seq dims.

    TPU-native form of the reference's sparse_attention capability
    (sparse_attention_op.cu computes dense scores then masks); see
    ops/pallas/splash_attention.py for the kernel design.
    """
    import numpy as _np

    from ...ops.pallas.splash_attention import splash_attention

    bm = _np.asarray(block_mask, bool)

    def fn(q, k, v):
        out = splash_attention(
            jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2), bm, is_causal, None, block_q, block_k)
        return out.swapaxes(1, 2)
    return apply_op("block_sparse_attention", fn, query, key, value)
