"""paddle_tpu.nn — layers and functional API.

~ python/paddle/nn/__init__.py.
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from .layer.layers import (  # noqa: F401
    Layer, LayerDict, LayerList, ParameterList, Sequential,
)
from .layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity, Dropout,
    Dropout2D, Dropout3D, Embedding, Flatten, Fold, Identity, Linear, Pad1D,
    Pad2D, Pad3D, PixelShuffle, PixelUnshuffle, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
    PairwiseDistance, Softmax2D,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
    AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    CTCLoss, HuberLoss, KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
    NLLLoss, SmoothL1Loss, TripletMarginLoss, HingeEmbeddingLoss,
    HSigmoidLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .layer.rnn import (  # noqa: F401
    BeamSearchDecoder, BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase,
    SimpleRNN, SimpleRNNCell, dynamic_decode,
)


class ClipGradByGlobalNorm:
    """~ paddle.nn.ClipGradByGlobalNorm (fluid/clip.py:441)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)


class ClipGradByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max
