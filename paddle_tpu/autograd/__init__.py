"""Autograd: define-by-run tape + functional transforms.

~ paddle.autograd (python/paddle/autograd/) backed by eager/backward.cc.
"""
from .tape import GradNode, backward, enable_grad, grad_enabled, no_grad  # noqa: F401


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad equivalent (python/paddle/fluid/dygraph/base.py grad).

    Computes grads of ``outputs`` wrt ``inputs`` without touching ``.grad``
    on other leaves. Implemented by running the tape backward on a cloned
    grad state.
    """
    from ..core.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    # snapshot existing .grad on every reachable leaf so only ``inputs``
    # observe this backward (paddle.grad does not pollute other .grads)
    leaves = set()
    stack = [t._grad_node for t in outputs if t._grad_node is not None]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for inp in node.inputs:
            if inp._grad_node is None:
                leaves.add(inp)
            else:
                stack.append(inp._grad_node)
    input_set = {id(t) for t in inputs}
    saved = [(t, t._grad) for t in leaves | set(inputs)]
    for t, _ in saved:
        t._grad = None
    backward(outputs, grad_outputs, retain_graph=bool(retain_graph))
    grads = {id(t): t._grad for t, _ in saved}
    for t, old in saved:
        t._grad = old
    results = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                f"tensor {t.name} was not used in the graph "
                "(pass allow_unused=True to return None)")
        results.append(g)
    return results


def __getattr__(name):
    # lazy: py_layer imports core.tensor which imports autograd.tape — a
    # top-level import here would be circular
    if name in ("PyLayer", "PyLayerContext"):
        from . import py_layer
        return getattr(py_layer, name)
    raise AttributeError(name)
