"""PyLayer: user-defined autograd functions.

~ python/paddle/autograd/py_layer.py (eager PyLayer over
paddle/fluid/eager/pylayer/). The tape records a node whose pullback calls
the user's static ``backward``; ``ctx.save_for_backward`` keeps forward
tensors (the TensorWrapper role).
"""
from __future__ import annotations

from typing import Any

from ..core.tensor import Tensor
from . import tape as _tape


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(
            f"use {cls.__name__}.apply(...) — PyLayer is not instantiated")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        import jax.numpy as jnp

        ctx = PyLayerContext()
        with _tape.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        if _tape.grad_enabled() and diff_inputs:
            out_avals = [(tuple(o.shape), o._value.dtype) for o in out_list
                         if isinstance(o, Tensor)]

            def vjp_fn(cts):
                if not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                ct_tensors = [Tensor(c) for c in cts]
                with _tape.no_grad():
                    grads = cls.backward(ctx, *ct_tensors)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                vals = []
                for g in grads:
                    if g is None:
                        vals.append(None)
                    else:
                        vals.append(g._value if isinstance(g, Tensor) else g)
                # align with diff_inputs: user returns one grad per
                # *tensor* input (paddle contract)
                tensor_inputs = [a for a in args if isinstance(a, Tensor)]
                out = []
                gi = 0
                for a in tensor_inputs:
                    g = vals[gi] if gi < len(vals) else None
                    gi += 1
                    if not a.stop_gradient:
                        out.append(g if g is not None
                                   else jnp.zeros(a.shape, a._value.dtype))
                return tuple(out)

            node = _tape.GradNode(cls.__name__, vjp_fn, diff_inputs,
                                  out_avals)
            idx = 0
            for o in out_list:
                if isinstance(o, Tensor):
                    o.stop_gradient = False
                    o._grad_node = node
                    o._output_index = idx
                    idx += 1
        return out_list[0] if single else tuple(out_list)


class LegacyPyLayer(PyLayer):
    pass
