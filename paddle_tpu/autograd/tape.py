"""Define-by-run autograd tape.

TPU-native equivalent of the reference's eager autograd engine:
- GradNode      ~ egr::GradNodeBase (paddle/fluid/eager/grad_node_info.h:165)
- backward()    ~ egr::Backward / RunBackward (paddle/fluid/eager/backward.cc:817,529)
- leaf accumulation ~ GradNodeAccumulation (paddle/fluid/eager/accumulation/)

Design difference from the reference: instead of one hand-written GradNode
class per op (codegened from backward.yaml), every op records a ``jax.vjp``
pullback closure at dispatch time. jax's VJP machinery *is* the grad-kernel
library, so op authors never write backward rules; the tape only supplies
define-by-run semantics (.backward() on a Python object graph) on top.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional

import jax
import numpy as np

_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set_grad_enabled(flag: bool) -> bool:
    prev = grad_enabled()
    _state.grad_enabled = flag
    return prev


@contextmanager
def no_grad():
    """paddle.no_grad equivalent (python/paddle/fluid/dygraph/base.py no_grad_)."""
    prev = _set_grad_enabled(False)
    try:
        yield
    finally:
        _set_grad_enabled(prev)


@contextmanager
def enable_grad():
    prev = _set_grad_enabled(True)
    try:
        yield
    finally:
        _set_grad_enabled(prev)


class GradNode:
    """One recorded op on the tape.

    Holds the vjp pullback, the differentiable input Tensors (edges to
    producer nodes / leaves), and metadata for constructing zero cotangents
    for unused outputs. ~ GradNodeBase with its GradSlotMeta edges.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "cotangents",
                 "pending", "__weakref__")

    def __init__(self, name: str, vjp_fn, inputs: List, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] — differentiable inputs
        self.out_avals = out_avals    # list[(shape, dtype)] for every output
        self.cotangents: Optional[list] = None
        self.pending = 0

    def add_cotangent(self, index: int, value) -> None:
        if self.cotangents is None:
            self.cotangents = [None] * len(self.out_avals)
        cur = self.cotangents[index]
        self.cotangents[index] = value if cur is None else cur + value

    def materialize_cotangents(self):
        import jax.numpy as jnp
        cts = self.cotangents or [None] * len(self.out_avals)
        out = []
        for ct, (shape, dtype) in zip(cts, self.out_avals):
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            out.append(ct)
        return tuple(out)


def _accumulate_leaf(tensor, value) -> None:
    # GradNodeAccumulation analog: accumulate into .grad on the leaf.
    from ..core.selected_rows import SelectedRows
    from ..core.tensor import Tensor
    if isinstance(value, SelectedRows):
        # sparse embedding grads stay as SelectedRows on the leaf (the
        # reference's is_sparse lookup_table grad); mixing with a dense
        # grad densifies via SelectedRows.__add__
        if tensor._grad is None:
            tensor._grad = value
        elif isinstance(tensor._grad, SelectedRows):
            tensor._grad = tensor._grad + value
        else:
            tensor._grad = Tensor(tensor._grad._value + value.to_dense(),
                                  stop_gradient=True)
        return
    if tensor._grad is None:
        tensor._grad = Tensor(value, stop_gradient=True)
    elif isinstance(tensor._grad, SelectedRows):
        tensor._grad = Tensor(tensor._grad.to_dense() + value,
                              stop_gradient=True)
    else:
        tensor._grad = Tensor(tensor._grad._value + value, stop_gradient=True)


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """Run reverse accumulation from ``tensors``.

    Mirrors egr::RunBackward (eager/backward.cc:529): seed cotangents, count
    in-graph dependencies, then queue-driven traversal calling each node's
    pullback and routing input cotangents to producer nodes or leaf grads.
    """
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    roots = []
    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                # loss is itself a leaf — grad is just the seed
                seed = jnp.ones(t.shape, t.dtype) if g is None else g._value
                _accumulate_leaf(t, seed)
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors "
                    f"(tensor shape {t.shape})")
            seed = jnp.ones(t.shape, t.dtype)
        else:
            seed = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node.add_cotangent(t._output_index, seed)
        roots.append(node)

    if not roots:
        return

    # Pass 1: discover reachable graph and count consumers per node.
    visited = set()
    stack = list(roots)
    order = []
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        order.append(node)
        for inp in node.inputs:
            prod = inp._grad_node
            if prod is not None:
                prod.pending += 1
                stack.append(prod)

    # Pass 2: queue-driven execution (ready = all consumers done).
    ready = [n for n in order if n.pending == 0]
    processed = 0
    while ready:
        node = ready.pop()
        processed += 1
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad graph for '{node.name}' was already freed; call "
                "backward(retain_graph=True) to backprop twice")
        cts = node.materialize_cotangents()
        if len(node.out_avals) == 1:
            in_cts = node.vjp_fn(cts[0])
        else:
            in_cts = node.vjp_fn(cts)
        node.cotangents = None  # always reset; retain_graph keeps only vjp_fn
        if not retain_graph:
            node.vjp_fn = None
        for inp, ct in zip(node.inputs, in_cts):
            prod = inp._grad_node
            if prod is None:
                if not inp.stop_gradient:
                    _accumulate_leaf(inp, ct)
            else:
                prod.add_cotangent(inp._output_index, ct)
                prod.pending -= 1
                if prod.pending == 0:
                    ready.append(prod)

    # Reset pending counts for any unprocessed nodes (disconnected pieces).
    for n in order:
        n.pending = 0

    # Post-backward hooks: the DataParallel grad-sync trigger (the role of
    # the reference's EagerReducer firing allreduce from GradNode hooks,
    # distributed/collective/reducer.h:86).
    for hook in list(_post_backward_hooks.values()):
        hook()


_post_backward_hooks: dict = {}
_hook_counter = [0]


def register_post_backward_hook(fn):
    """Register fn() to run after every backward(). Returns a handle with
    .remove()."""
    _hook_counter[0] += 1
    hid = _hook_counter[0]
    _post_backward_hooks[hid] = fn

    class _Handle:
        def remove(self):
            _post_backward_hooks.pop(hid, None)

    return _Handle()
