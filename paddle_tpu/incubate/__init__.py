"""paddle_tpu.incubate — experimental features.

~ python/paddle/incubate/ (fused transformer layers, MoE, functional
autograd). Fused layers route to the Pallas kernels; MoE lives in
incubate.distributed.models.moe mirroring the reference layout.
"""
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import autotune  # noqa: F401
