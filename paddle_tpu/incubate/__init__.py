"""paddle_tpu.incubate — experimental features.

~ python/paddle/incubate/ (fused transformer layers, MoE, functional
autograd). Fused layers route to the Pallas kernels; MoE lives in
incubate.distributed.models.moe mirroring the reference layout.
"""
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import autotune  # noqa: F401
from . import checkpoint  # noqa: F401
from . import multiprocessing  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .graph_ops import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, segment_max, segment_mean, segment_min, segment_sum,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
