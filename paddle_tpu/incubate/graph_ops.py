"""Segment reductions + graph sampling/message-passing ops.

~ python/paddle/incubate/operators/ (segment_sum/mean/max/min over phi
segment_pool kernels; graph_send_recv, graph_reindex, graph_khop_sampler,
graph_sample_neighbors under incubate/graph_*; softmax_mask_fuse ops from
operators/fused/fused_softmax_mask_op.cu).

TPU notes: segment reductions lower to jax.ops.segment_* (XLA scatter —
fine on TPU for moderate segment counts); neighbor sampling is data
dependent so it is a host op like the reference's CPU sampling kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def _seg(op_name, jfn, x, segment_ids):
    def fn(v, ids):
        n = int(np.asarray(ids).max()) + 1 if not isinstance(
            ids, jax.core.Tracer) else None
        if n is None:
            raise ValueError("segment ops need concrete segment_ids under "
                             "tracing; pass num_segments explicitly")
        return jfn(v, ids, num_segments=n)
    return apply_op(op_name, fn, x, segment_ids)


def segment_sum(data, segment_ids):
    return _seg("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids):
    def fn(v, ids):
        n = int(np.asarray(ids).max()) + 1
        s = jax.ops.segment_sum(v, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, v.dtype), ids,
                                  num_segments=n)
        shape = (n,) + (1,) * (v.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1.0)
    return apply_op("segment_mean", fn, data, segment_ids)


def segment_max(data, segment_ids):
    return _seg("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids):
    return _seg("segment_min", jax.ops.segment_min, data, segment_ids)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None):
    """~ incubate.graph_send_recv: gather rows at src, segment-reduce into
    dst (one message-passing step)."""
    def fn(v, src, dst):
        msgs = v[src]
        n = out_size or v.shape[0]
        pt = pool_type.lower()
        if pt == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if pt == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dst, v.dtype), dst,
                                    num_segments=n)
            return s / jnp.maximum(c.reshape((n,) + (1,) * (v.ndim - 1)),
                                   1.0)
        if pt == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n)
        if pt == "min":
            return jax.ops.segment_min(msgs, dst, num_segments=n)
        raise ValueError(f"unknown pool_type {pool_type}")
    return apply_op("graph_send_recv", fn, x, src_index, dst_index)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False):
    """~ incubate.graph_reindex: compress (x ∪ neighbors) node ids into a
    dense [0, n) range. Host op (dynamic output ids)."""
    xs = np.asarray(x._value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._value if isinstance(neighbors, Tensor)
                    else neighbors)
    uniq = list(dict.fromkeys(xs.tolist() + nb.tolist()))
    remap = {v: i for i, v in enumerate(uniq)}
    reindex_src = np.asarray([remap[v] for v in nb.tolist()], np.int64)
    # each center node i emits count[i] edges; dst is its dense id repeated
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count)
    reindex_dst = np.repeat(np.asarray([remap[v] for v in xs.tolist()],
                                       np.int64), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(uniq, np.int64))))


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           flag_perm_buffer=False):
    """~ incubate.graph_sample_neighbors over a CSC graph: sample up to
    ``sample_size`` in-neighbors per input node. Host op."""
    from ..core.generator import default_generator
    r = np.asarray(row._value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes._value if isinstance(input_nodes, Tensor)
                       else input_nodes)
    rng = np.random.default_rng(
        int(np.asarray(default_generator().next_key())[1]))
    out, counts = [], []
    for n in nodes.tolist():
        nbrs = r[cp[n]:cp[n + 1]]
        if sample_size > 0 and len(nbrs) > sample_size:
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out.append(nbrs)
        counts.append(len(nbrs))
    flat = np.concatenate(out) if out else np.zeros(0, r.dtype)
    return (Tensor(jnp.asarray(flat)),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False):
    """~ incubate.graph_khop_sampler: multi-hop neighbor sampling +
    reindex. Host op."""
    cur = input_nodes
    all_edges_src, all_edges_dst = [], []
    frontier = np.asarray(cur._value if isinstance(cur, Tensor) else cur)
    seen = list(dict.fromkeys(frontier.tolist()))
    for k in sample_sizes:
        nbrs, counts = graph_sample_neighbors(row, colptr,
                                              Tensor(jnp.asarray(frontier)),
                                              sample_size=k)
        nb = np.asarray(nbrs._value)
        cnt = np.asarray(counts._value)
        dst = np.repeat(frontier, cnt)
        all_edges_src.append(nb)
        all_edges_dst.append(dst)
        frontier = np.asarray(list(dict.fromkeys(nb.tolist())))
        for v in frontier.tolist():
            if v not in seen:
                seen.append(v)
    src = np.concatenate(all_edges_src) if all_edges_src else np.zeros(0)
    dst = np.concatenate(all_edges_dst) if all_edges_dst else np.zeros(0)
    remap = {v: i for i, v in enumerate(seen)}
    return (Tensor(jnp.asarray(np.asarray([remap[v] for v in src.tolist()],
                                          np.int64))),
            Tensor(jnp.asarray(np.asarray([remap[v] for v in dst.tolist()],
                                          np.int64))),
            Tensor(jnp.asarray(np.asarray(seen, np.int64))),
            Tensor(jnp.asarray(np.asarray(
                [len(s) for s in all_edges_src], np.int64))))


def softmax_mask_fuse(x, mask, name=None):
    """~ incubate.softmax_mask_fuse (fused_softmax_mask_op.cu): softmax of
    x + mask along the last dim — XLA fuses add+softmax into one kernel."""
    return apply_op("softmax_mask_fuse",
                    lambda v, m: jax.nn.softmax(v + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """~ incubate.softmax_mask_fuse_upper_triangle: causal-masked softmax
    (scores above the diagonal suppressed)."""
    def fn(v):
        L = v.shape[-1]
        mask = jnp.tril(jnp.ones((v.shape[-2], L), bool))
        neg = jnp.finfo(v.dtype).min
        return jax.nn.softmax(jnp.where(mask, v, neg), axis=-1)
    return apply_op("softmax_mask_fuse_upper_triangle", fn, x)
