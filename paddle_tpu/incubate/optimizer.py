"""Incubate optimizers: LookAhead, ModelAverage.

~ python/paddle/incubate/optimizer/ (lookahead.py, modelaverage.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import no_grad
from ..core.tensor import Tensor
from ..optimizer import Optimizer


class LookAhead(Optimizer):
    """~ incubate/optimizer/lookahead.py: slow/fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._step_num = 0

    @property
    def _parameters(self):
        return self.inner_optimizer._parameters

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self.inner_optimizer._parameters:
                if id(p) not in self._slow:
                    self._slow[id(p)] = p._value
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = slow
                p._value = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step_num": self._step_num}

    def set_state_dict(self, st):
        self.inner_optimizer.set_state_dict(st.get("inner", {}))
        self._step_num = st.get("step_num", 0)


class ModelAverage(Optimizer):
    """~ incubate/optimizer/modelaverage.py: EMA of parameters with
    apply/restore context."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters)
        self.rate = average_window_rate
        self._sum = {}
        self._count = 0
        self._backup = None

    @no_grad()
    def step(self):
        self._count += 1
        for p in self._parameters:
            acc = self._sum.get(id(p))
            self._sum[id(p)] = p._value if acc is None else acc + p._value

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._parameters}
        for p in self._parameters:
            if id(p) in self._sum and self._count:
                p._value = self._sum[id(p)] / self._count
        return _RestoreCtx(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameters:
                if id(p) in self._backup:
                    p._value = self._backup[id(p)]
            self._backup = None


class _RestoreCtx:
    def __init__(self, ma):
        self.ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.ma.restore()
        return False
