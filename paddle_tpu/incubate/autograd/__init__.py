"""Functional autograd transforms.

~ python/paddle/incubate/autograd/ (jacobian/hessian/vjp/jvp). These map
1:1 onto jax transforms over Tensor-valued functions.
"""
from __future__ import annotations

import jax

from ...core.tensor import Tensor


def _fn_on_arrays(func):
    def f(*arrays):
        t_args = [Tensor(a) for a in arrays]
        out = func(*t_args)
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value
    return f


def _vals(xs):
    if isinstance(xs, Tensor):
        return (xs._value,), True
    return tuple(x._value for x in xs), False


def vjp(func, xs, v=None):
    vals, single = _vals(xs)
    out, pullback = jax.vjp(_fn_on_arrays(func), *vals)
    if v is None:
        import jax.numpy as jnp
        seed = jnp.ones_like(out) if not isinstance(out, tuple) \
            else tuple(jnp.ones_like(o) for o in out)
    else:
        seed = v._value if isinstance(v, Tensor) else \
            tuple(t._value for t in v)
    grads = pullback(seed)
    outs = Tensor(out) if not isinstance(out, tuple) \
        else tuple(Tensor(o) for o in out)
    gs = [Tensor(g) for g in grads]
    return outs, gs[0] if single else gs


def jvp(func, xs, v=None):
    vals, single = _vals(xs)
    import jax.numpy as jnp
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        tangents = (v._value,) if isinstance(v, Tensor) else \
            tuple(t._value for t in v)
    out, jv = jax.jvp(_fn_on_arrays(func), vals, tangents)
    outs = Tensor(out) if not isinstance(out, tuple) \
        else tuple(Tensor(o) for o in out)
    return outs, Tensor(jv) if not isinstance(jv, tuple) \
        else tuple(Tensor(j) for j in jv)


class Jacobian:
    """~ incubate/autograd/functional.py Jacobian — lazy J[i][j] view."""

    def __init__(self, func, xs, is_batched=False):
        vals, single = _vals(xs)
        f = _fn_on_arrays(func)
        self._jac = (jax.jacrev(f, argnums=tuple(range(len(vals))))(*vals))
        if single:
            self._jac = self._jac[0]

    def __getitem__(self, idx):
        import numpy as np
        return Tensor(np.asarray(self._jac)[idx])

    @property
    def shape(self):
        return list(self._jac.shape)


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        vals, single = _vals(xs)
        f = _fn_on_arrays(func)
        self._h = jax.hessian(f)(*vals)

    def __getitem__(self, idx):
        import numpy as np
        return Tensor(np.asarray(self._h)[idx])


def jacobian(func, xs, create_graph=False):
    return Jacobian(func, xs)


def hessian(func, xs, create_graph=False):
    return Hessian(func, xs)
