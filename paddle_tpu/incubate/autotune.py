"""paddle.incubate.autotune shim over ops/autotune.

~ python/paddle/incubate/autotune.py set_config({"kernel": {"enable": ...,
"tuning_range": ...}}) driving phi/kernels/autotune/switch_autotune.cc.
"""
from ..ops.autotune import (  # noqa: F401
    AutoTuneCache, autotune, autotune_enabled, cache, disable_autotune,
    enable_autotune, tuned_flash_attention,
)


def set_config(config=None):
    if config is None:
        enable_autotune()
        return
    kernel = (config or {}).get("kernel", {})
    if kernel.get("enable", False):
        enable_autotune()
    else:
        disable_autotune()
