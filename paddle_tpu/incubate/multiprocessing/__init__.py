"""Tensor sharing across processes.

~ python/paddle/incubate/multiprocessing (reductions.py:104
reduce_tensor): registers a ForkingPickler reduction for Tensor so
tensors crossing multiprocessing queues/pipes travel as shared-memory
segments instead of pickled byte copies. TPU-native shape: device arrays
are host-materialized once into a multiprocessing.shared_memory block
(the file-descriptor LoDTensor IPC of the reference); the receiver maps
the block zero-copy as numpy and re-wraps. An LRU keeps segments alive in
the producer until the consumer has had a chance to map them.

Use ``multiprocessing.get_context("spawn")`` for the worker processes: a
forked child of a jax-active parent deadlocks on first device access
(XLA's threads don't survive fork), while spawn starts a clean
interpreter — the same constraint the reference documents for CUDA
tensors.
"""
from __future__ import annotations

import atexit
from collections import OrderedDict
from multiprocessing import shared_memory
from multiprocessing.reduction import ForkingPickler

import numpy as np

from ...core.tensor import Tensor

__all__ = ["init_reductions", "reduce_tensor", "rebuild_tensor",
           "allocate_shared", "LRUSharedCache"]


class LRUSharedCache(OrderedDict):
    """~ reductions.py:49 — bounded cache of producer-side shm handles.

    Ownership protocol: the CONSUMER unlinks a segment after rebuilding
    (it copies the data out), so eviction here only closes the producer's
    handle — an unread in-flight segment stays alive no matter how many
    tensors were sent after it. Segments never consumed (dropped
    messages) are unlinked at producer exit."""

    LIMIT = 128

    def put(self, key, shm):
        self[key] = shm
        self.move_to_end(key)
        while len(self) > self.LIMIT:
            _k, old = self.popitem(last=False)
            self._evicted_names.append(old.name)
            try:
                old.close()  # close only; consumer owns the unlink
            except OSError:
                pass

    _evicted_names: list = []


_producer_cache = LRUSharedCache()


@atexit.register
def _cleanup():
    # reap everything this producer created that no consumer unlinked
    for shm in _producer_cache.values():
        try:
            shm.close()
            shm.unlink()
        except (FileNotFoundError, OSError):
            pass
    for name in LRUSharedCache._evicted_names:
        try:
            shared_memory.SharedMemory(name=name).unlink()
        except (FileNotFoundError, OSError):
            pass
    _producer_cache.clear()


def allocate_shared(arr: np.ndarray):
    """Copy ``arr`` into a fresh shared-memory block; returns (shm, view)."""
    arr = np.ascontiguousarray(arr)
    shm = shared_memory.SharedMemory(create=True, size=max(1, arr.nbytes))
    view = np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)
    view[...] = arr
    return shm, view


def rebuild_tensor(shm_name, shape, dtype_str, stop_gradient):
    """Consumer side: map the segment and wrap (~ rebuild_tensor :87)."""
    shm = shared_memory.SharedMemory(name=shm_name)
    arr = np.ndarray(shape, np.dtype(dtype_str), buffer=shm.buf)
    # copy out: the producer's LRU may unlink the segment later, and jax
    # will anyway copy host->device on first use
    t = Tensor(np.array(arr), stop_gradient=stop_gradient)
    # the consumer owns the unlink (see LRUSharedCache): data is copied
    # out, so release the name now; the producer's atexit double-unlink
    # attempts are FileNotFoundError-guarded
    try:
        shm.unlink()
    except (FileNotFoundError, OSError):
        pass
    shm.close()
    return t


def reduce_tensor(t: Tensor):
    """Producer side (~ reduce_tensor :104): host-materialize once, ship
    the segment name + metadata."""
    arr = np.asarray(t._value)
    shm, _ = allocate_shared(arr)
    _producer_cache.put(shm.name, shm)
    return (rebuild_tensor,
            (shm.name, tuple(arr.shape), arr.dtype.str, t.stop_gradient))


_initialized = False


def init_reductions():
    """Register the Tensor reduction with ForkingPickler
    (~ reductions.py init_reductions). Idempotent."""
    global _initialized
    if _initialized:
        return
    ForkingPickler.register(Tensor, reduce_tensor)
    _initialized = True
