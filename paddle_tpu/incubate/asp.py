"""ASP: 2:4 structured sparsity.

~ python/paddle/incubate/asp (static/sparsity + fluid/contrib/sparsity):
prune weights to the 2-out-of-4 pattern the MXU-era sparse units exploit,
keep masks, and re-apply after each optimizer step.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

_masks: Dict[int, "jnp.ndarray"] = {}


def compute_mask_2d(weight: np.ndarray, n=2, m=4) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive elements (last dim)."""
    w = np.asarray(weight)
    orig_shape = w.shape
    flat = w.reshape(-1)
    pad = (-len(flat)) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat).reshape(-1, m)
    thresh_idx = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, thresh_idx, True, axis=1)
    mask = mask.reshape(-1)[:w.size].reshape(orig_shape)
    return mask


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d"):
    """~ asp.prune_model: prune eligible weights, remember masks."""
    for name, p in model.named_parameters():
        if p.ndim < 2 or "bias" in name:
            continue
        mask = compute_mask_2d(p.numpy(), n, m)
        _masks[id(p)] = jnp.asarray(mask)
        p._value = p._value * _masks[id(p)].astype(p._value.dtype)
    return model


def decorate(optimizer):
    """~ asp.decorate: re-apply masks after each step."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for p in optimizer._parameters:
            mask = _masks.get(id(p))
            if mask is not None:
                p._value = p._value * mask.astype(p._value.dtype)
    optimizer.step = step
    return optimizer


def check_sparsity(weight, n=2, m=4) -> bool:
    w = np.asarray(weight._value if isinstance(weight, Tensor) else weight)
    flat = w.reshape(-1)
    pad = (-len(flat)) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = flat.reshape(-1, m)
    return bool(((groups != 0).sum(axis=1) <= n).all())


def reset_masks():
    _masks.clear()
