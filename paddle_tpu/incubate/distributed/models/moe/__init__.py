"""Mixture-of-Experts with expert parallelism.

~ python/paddle/incubate/distributed/models/moe/ (moe_layer.py:233 MoELayer,
dispatch :97-162; gate/gshard_gate.py, switch_gate.py; comm via
global_scatter/global_gather CUDA a2a ops).

TPU-native design (SPMD, static shapes — SURVEY.md §7 hard-part #4): the
gate emits a FIXED-capacity dispatch tensor (one-hot combine/dispatch
einsums, the GShard formulation). Experts are a single stacked weight
tensor with the expert dim annotated P('expert', ...); under pjit the
dispatch einsum over the sharded expert dim compiles to the all_to_all the
reference codes by hand in global_scatter_op.cu. Tokens over capacity are
dropped (reference behavior for fixed capacity).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..... import nn
from .....core import generator as _gen
from .....core.tensor import Tensor
from .....nn import functional as F
from .....ops.dispatch import apply_op


def top1_gating(logits, capacity, noise_key=None, eps_std=0.0):
    """Switch-style top-1 gate with load-balancing aux loss.

    Returns (dispatch (T,E,C) bool, combine (T,E,C) float, aux_loss).
    """
    T, E = logits.shape
    if noise_key is not None and eps_std > 0:
        logits = logits + eps_std * jax.random.normal(noise_key, logits.shape)
    probs = jax.nn.softmax(logits, -1)
    expert = jnp.argmax(probs, -1)  # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # aux loss (Switch eq. 4): E * sum(fraction_tokens * fraction_probs)
    frac_tokens = jnp.mean(onehot, 0)
    frac_probs = jnp.mean(probs, 0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    # position of each token within its expert queue
    pos = (jnp.cumsum(onehot, 0) - 1.0) * onehot  # (T,E)
    pos = jnp.sum(pos, -1).astype(jnp.int32)  # (T,)
    keep = pos < capacity
    gate_val = jnp.sum(probs * onehot, -1) * keep
    dispatch = (jax.nn.one_hot(expert, E, dtype=jnp.float32)[:, :, None]
                * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
    dispatch = dispatch * keep[:, None, None]
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine, aux


def top2_gating(logits, capacity, noise_key=None):
    """GShard top-2 gate."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    g1 = jnp.argmax(probs, -1)
    p1 = jnp.max(probs, -1)
    probs2 = probs * (1 - jax.nn.one_hot(g1, E, dtype=probs.dtype))
    g2 = jnp.argmax(probs2, -1)
    p2 = jnp.max(probs2, -1)
    denom = jnp.maximum(p1 + p2, 1e-9)
    p1, p2 = p1 / denom, p2 / denom

    oh1 = jax.nn.one_hot(g1, E, dtype=jnp.float32)
    oh2 = jax.nn.one_hot(g2, E, dtype=jnp.float32)
    aux = E * jnp.sum(jnp.mean(oh1, 0) * jnp.mean(probs, 0))

    pos1 = (jnp.sum((jnp.cumsum(oh1, 0) - 1.0) * oh1, -1)).astype(jnp.int32)
    # second choice queues stack after first-choice counts
    count1 = jnp.sum(oh1, 0, keepdims=True)
    pos2 = (jnp.sum((jnp.cumsum(oh2, 0) - 1.0) * oh2 + count1 * oh2, -1)
            ).astype(jnp.int32)
    keep1 = pos1 < capacity
    keep2 = pos2 < capacity

    def disp(g, pos, keep, p):
        d = (jax.nn.one_hot(g, E, dtype=jnp.float32)[:, :, None]
             * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        return d, d * (p * keep)[:, None, None]

    d1, c1 = disp(g1, pos1, keep1, p1)
    d2, c2 = disp(g2, pos2, keep2, p2)
    return jnp.maximum(d1, d2), c1 + c2, aux


def topk_gating(logits, capacity, k):
    """Generalized GShard-style top-k gate (k >= 2): the fine-grained
    DeepSeek/Qwen routing regimes use top-4/top-8 over many small
    experts. Iteratively takes the argmax k times (static unroll),
    normalizes the k gate probs, and queues each choice's capacity
    positions AFTER all earlier choices' per-expert counts — for k=2
    this reproduces ``top2_gating`` exactly (tested)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    remaining = probs
    picks = []
    for _ in range(k):
        g = jnp.argmax(remaining, -1)
        p = jnp.max(remaining, -1)
        oh = jax.nn.one_hot(g, E, dtype=jnp.float32)
        remaining = remaining * (1 - oh)
        picks.append((g, p, oh))
    denom = jnp.maximum(sum(p for _, p, _ in picks), 1e-9)
    aux = E * jnp.sum(jnp.mean(picks[0][2], 0) * jnp.mean(probs, 0))

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    prior_counts = jnp.zeros((1, E), jnp.float32)
    for g, p, oh in picks:
        pos = (jnp.sum((jnp.cumsum(oh, 0) - 1.0) * oh
                       + prior_counts * oh, -1)).astype(jnp.int32)
        keep = pos < capacity
        d = (oh[:, :, None]
             * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        dispatch = jnp.maximum(dispatch, d)
        combine = combine + d * ((p / denom) * keep)[:, None, None]
        prior_counts = prior_counts + jnp.sum(oh, 0, keepdims=True)
    return dispatch, combine, aux


def expert_choice_gating(logits, capacity):
    """Expert-choice routing (Zhou et al. 2022): each EXPERT picks its
    top-``capacity`` tokens instead of tokens picking experts. Load
    balance is exact by construction (every expert processes exactly C
    tokens), so there is no aux loss and no overflow dropping; a token
    may be picked by 0..E experts. Static shapes throughout — the
    top_k is over a fixed (E, T) score matrix, TPU-friendly.

    Returns (dispatch (T,E,C), combine (T,E,C), aux=0).
    """
    T, E = logits.shape
    capacity = min(capacity, T)  # top_k requires k <= T (tiny batches /
    #                              many experts; token-routing gates
    #                              tolerate cap > T but top_k raises)
    probs = jax.nn.softmax(logits, -1)           # per-token over experts
    g, idx = jax.lax.top_k(probs.T, capacity)    # (E, C): weights, tokens
    dispatch = jnp.transpose(
        jax.nn.one_hot(idx, T, dtype=jnp.float32), (2, 0, 1))  # (T,E,C)
    combine = dispatch * g[None, :, :]
    return dispatch, combine, jnp.zeros((), jnp.float32)


class BaseGate(nn.Layer):
    """~ gate/base_gate.py."""

    routing = "token"  # tokens pick experts (gshard/switch family)

    def __init__(self, d_model, num_experts):
        super().__init__()
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)
        self.num_experts = num_experts


class SwitchGate(BaseGate):
    top_k = 1


class GShardGate(BaseGate):
    top_k = 2


class NaiveGate(BaseGate):
    top_k = 2


class ExpertChoiceGate(BaseGate):
    """Experts pick tokens; top_k only feeds the capacity formula
    (C = top_k * capacity_factor * T / E)."""

    routing = "expert"
    top_k = 2


class MoELayer(nn.Layer):
    """~ moe_layer.py:233.

    experts: stacked FFN weights (E, d_model, d_hidden) / (E, d_hidden,
    d_model), expert dim annotated over the 'expert' mesh axis.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.25, top_k=None, group=None,
                 recompute_interval=0, name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        if isinstance(gate, str):
            gate_cls = {"gshard": GShardGate, "switch": SwitchGate,
                        "naive": NaiveGate,
                        "expert_choice": ExpertChoiceGate}[gate]
            self.gate = gate_cls(d_model, num_experts)
        else:
            self.gate = gate
        self.top_k = top_k or getattr(self.gate, "top_k", 2)

        from ..... import nn as _nn
        from .....nn import initializer as init
        self.w_in = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=init.XavierNormal())
        self.w_out = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=init.XavierNormal())
        self.w_in.sharding_spec = P("expert", None, "model")
        self.w_out.sharding_spec = P("expert", "model", None)
        self.aux_loss = None

    def capacity(self, num_tokens):
        cap = int(math.ceil(self.top_k * self.capacity_factor * num_tokens
                            / self.num_experts))
        return max(cap, 4)

    def forward(self, x):
        B, S, H = x.shape
        T = B * S
        cap = self.capacity(T)
        gate_logits = self.gate.wg(x)  # (B,S,E)
        topk = self.top_k
        key = _gen.next_key() if self.training else None

        routing = getattr(self.gate, "routing", "token")

        def fused(xv, gl, w_in, w_out):
            xt = xv.reshape(T, H)
            glt = gl.reshape(T, self.num_experts).astype(jnp.float32)
            if routing == "expert":
                dispatch, combine, aux = expert_choice_gating(glt, cap)
            elif topk == 1:
                dispatch, combine, aux = top1_gating(glt, cap, key,
                                                     0.01 if key is not None
                                                     else 0.0)
            elif topk == 2:
                dispatch, combine, aux = top2_gating(glt, cap)
            else:
                dispatch, combine, aux = topk_gating(glt, cap, topk)
            # (T,E,C) x (T,H) -> (E,C,H): the all_to_all boundary under SPMD
            expert_in = jnp.einsum("tec,th->ech",
                                   dispatch.astype(xt.dtype), xt)
            h = jnp.einsum("ech,ehf->ecf", expert_in, w_in)
            h = jax.nn.gelu(h)
            expert_out = jnp.einsum("ecf,efh->ech", h, w_out)
            out = jnp.einsum("tec,ech->th", combine.astype(xt.dtype),
                             expert_out)
            return out.reshape(B, S, H), aux.astype(xt.dtype)

        out, aux = apply_op("moe_layer", fused, x, gate_logits, self.w_in,
                            self.w_out)
        self.aux_loss = aux
        return out
