"""Mixture-of-Experts with expert parallelism.

~ python/paddle/incubate/distributed/models/moe/ (moe_layer.py:233 MoELayer,
dispatch :97-162; gate/gshard_gate.py, switch_gate.py; comm via
global_scatter/global_gather CUDA a2a ops).

TPU-native design (SPMD, static shapes — SURVEY.md §7 hard-part #4): the
gate emits a FIXED-capacity dispatch tensor (one-hot combine/dispatch
einsums, the GShard formulation). Experts are a single stacked weight
tensor with the expert dim annotated P('expert', ...); under pjit the
dispatch einsum over the sharded expert dim compiles to the all_to_all the
reference codes by hand in global_scatter_op.cu. Tokens over capacity are
dropped (reference behavior for fixed capacity).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..... import nn
from .....core import generator as _gen
from .....core.tensor import Tensor
from .....nn import functional as F
from .....ops.dispatch import apply_op


def top1_gating(logits, capacity, noise_key=None, eps_std=0.0):
    """Switch-style top-1 gate with load-balancing aux loss.

    Returns (dispatch (T,E,C) bool, combine (T,E,C) float, aux_loss).
    """
    T, E = logits.shape
    if noise_key is not None and eps_std > 0:
        logits = logits + eps_std * jax.random.normal(noise_key, logits.shape)
    probs = jax.nn.softmax(logits, -1)
    expert = jnp.argmax(probs, -1)  # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # aux loss (Switch eq. 4): E * sum(fraction_tokens * fraction_probs)
    frac_tokens = jnp.mean(onehot, 0)
    frac_probs = jnp.mean(probs, 0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    # position of each token within its expert queue
    pos = (jnp.cumsum(onehot, 0) - 1.0) * onehot  # (T,E)
    pos = jnp.sum(pos, -1).astype(jnp.int32)  # (T,)
    keep = pos < capacity
    gate_val = jnp.sum(probs * onehot, -1) * keep
    dispatch = (jax.nn.one_hot(expert, E, dtype=jnp.float32)[:, :, None]
                * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
    dispatch = dispatch * keep[:, None, None]
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine, aux


def top2_gating(logits, capacity, noise_key=None):
    """GShard top-2 gate."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    g1 = jnp.argmax(probs, -1)
    p1 = jnp.max(probs, -1)
    probs2 = probs * (1 - jax.nn.one_hot(g1, E, dtype=probs.dtype))
    g2 = jnp.argmax(probs2, -1)
    p2 = jnp.max(probs2, -1)
    denom = jnp.maximum(p1 + p2, 1e-9)
    p1, p2 = p1 / denom, p2 / denom

    oh1 = jax.nn.one_hot(g1, E, dtype=jnp.float32)
    oh2 = jax.nn.one_hot(g2, E, dtype=jnp.float32)
    aux = E * jnp.sum(jnp.mean(oh1, 0) * jnp.mean(probs, 0))

    pos1 = (jnp.sum((jnp.cumsum(oh1, 0) - 1.0) * oh1, -1)).astype(jnp.int32)
    # second choice queues stack after first-choice counts
    count1 = jnp.sum(oh1, 0, keepdims=True)
    pos2 = (jnp.sum((jnp.cumsum(oh2, 0) - 1.0) * oh2 + count1 * oh2, -1)
            ).astype(jnp.int32)
    keep1 = pos1 < capacity
    keep2 = pos2 < capacity

    def disp(g, pos, keep, p):
        d = (jax.nn.one_hot(g, E, dtype=jnp.float32)[:, :, None]
             * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        return d, d * (p * keep)[:, None, None]

    d1, c1 = disp(g1, pos1, keep1, p1)
    d2, c2 = disp(g2, pos2, keep2, p2)
    return jnp.maximum(d1, d2), c1 + c2, aux


def _topk_picks(probs, k):
    """Shared pick loop: argmax k times with chosen experts masked out.
    Returns ([(expert_ids, probs, one_hot)] * k, aux_loss) — both the
    dense gating family and the index-form gate build on this, so the
    production (indexed) path and its dense oracle stay structurally in
    sync."""
    E = probs.shape[1]
    remaining = probs
    picks = []
    for _ in range(k):
        g = jnp.argmax(remaining, -1)
        p = jnp.max(remaining, -1)
        oh = jax.nn.one_hot(g, E, dtype=jnp.float32)
        remaining = remaining * (1 - oh)
        picks.append((g, p, oh))
    aux = E * jnp.sum(jnp.mean(picks[0][2], 0) * jnp.mean(probs, 0))
    return picks, aux


def topk_gating(logits, capacity, k):
    """Generalized GShard-style top-k gate (k >= 2): the fine-grained
    DeepSeek/Qwen routing regimes use top-4/top-8 over many small
    experts. Iteratively takes the argmax k times (static unroll),
    normalizes the k gate probs, and queues each choice's capacity
    positions AFTER all earlier choices' per-expert counts — for k=2
    this reproduces ``top2_gating`` exactly (tested)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    picks, aux = _topk_picks(probs, k)
    denom = jnp.maximum(sum(p for _, p, _ in picks), 1e-9)

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    prior_counts = jnp.zeros((1, E), jnp.float32)
    for g, p, oh in picks:
        pos = (jnp.sum((jnp.cumsum(oh, 0) - 1.0) * oh
                       + prior_counts * oh, -1)).astype(jnp.int32)
        keep = pos < capacity
        d = (oh[:, :, None]
             * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
        d = d * keep[:, None, None]
        dispatch = jnp.maximum(dispatch, d)
        combine = combine + d * ((p / denom) * keep)[:, None, None]
        prior_counts = prior_counts + jnp.sum(oh, 0, keepdims=True)
    return dispatch, combine, aux


def topk_gating_idx(logits, capacity, k, noise_key=None, eps_std=0.0):
    """Index-form gating: the same expert choices, queue positions and
    combine weights as the dense (T,E,C) gating family (top1/top2/topk),
    returned per (token, choice) for the scatter/gather dispatch path.

    The dense one-hot dispatch einsum costs O(T*E*C*H) = O(T^2*k*cf*H)
    MACs — quadratic in tokens (the round-4 chip row measured 0.294
    activated MFU on it). Index form carries only (T,k) ids/positions;
    dispatch becomes a scatter-add and combine a gather, O(T*k*H) data
    movement with zero matmul FLOPs. Dense equivalence is tested
    (tests/test_moe_dispatch.py).

    Returns (eids (T,k) int32, pos (T,k) int32, keep (T,k) bool,
    w (T,k) f32 — zeroed where dropped, aux).
    """
    T, E = logits.shape
    if noise_key is not None and eps_std > 0:
        logits = logits + eps_std * jax.random.normal(noise_key, logits.shape)
    probs = jax.nn.softmax(logits, -1)
    picks, aux = _topk_picks(probs, k)
    if k == 1:
        weights = [picks[0][1]]  # Switch combine weight = raw top-1 prob
    else:
        denom = jnp.maximum(sum(p for _, p, _ in picks), 1e-9)
        weights = [p / denom for _, p, _ in picks]
    eids, poss, keeps, ws = [], [], [], []
    prior = jnp.zeros((1, E), jnp.float32)
    for (g, _, oh), w in zip(picks, weights):
        # position within the expert queue; later choices stack after
        # all earlier choices' per-expert counts (as in top2/topk dense)
        pos = jnp.sum((jnp.cumsum(oh, 0) - 1.0) * oh + prior * oh,
                      -1).astype(jnp.int32)
        keep = pos < capacity
        eids.append(g.astype(jnp.int32))
        poss.append(jnp.where(keep, pos, 0))
        keeps.append(keep)
        ws.append(w * keep)
        prior = prior + jnp.sum(oh, 0, keepdims=True)
    return (jnp.stack(eids, 1), jnp.stack(poss, 1), jnp.stack(keeps, 1),
            jnp.stack(ws, 1), aux)


def indexed_dispatch(xt, eids, pos, keep, capacity, num_experts):
    """(T,H) tokens -> (E,C,H) expert inputs by scatter-add.

    Kept (token, choice) pairs hold unique (expert, position) slots by
    construction (queue positions), so add == set; dropped pairs have
    masked (zero) updates. Under pjit with the expert dim sharded this
    is the all_to_all boundary the reference codes by hand in
    global_scatter_op.cu.cc:1.
    """
    T, H = xt.shape
    k = eids.shape[1]
    flat = (eids * capacity + pos).reshape(T * k)
    upd = jnp.broadcast_to(xt[:, None, :], (T, k, H)).reshape(T * k, H)
    upd = upd * keep.reshape(T * k, 1).astype(xt.dtype)
    buf = jnp.zeros((num_experts * capacity, H), xt.dtype)
    buf = buf.at[flat].add(upd, mode="drop", unique_indices=False)
    return buf.reshape(num_experts, capacity, H)


def inverted_dispatch(xt, eids, pos, keep, capacity, num_experts):
    """Same (E,C,H) expert inputs as ``indexed_dispatch``, built by
    slot INVERSION + row gather instead of a float scatter: the only
    scatter is (T*k,) int32 slot->token indices (tiny); the H-wide data
    movement is a dense gather, which the TPU executes far faster than
    row scatter-adds. Dropped pairs target a sentinel slot; empty slots
    gather a zero row via a sentinel token."""
    T, H = xt.shape
    k = eids.shape[1]
    EC = num_experts * capacity
    flat = jnp.where(keep, eids * capacity + pos, EC).reshape(T * k)
    tok_ids = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[:, None], (T, k)).reshape(T * k)
    inv = jnp.full((EC + 1,), T, jnp.int32).at[flat].set(
        tok_ids, mode="drop")
    # empty/dropped slots hold the out-of-range sentinel T: take with
    # fill produces their zero rows without copying xt to append one
    return jnp.take(xt, inv[:EC], axis=0, mode="fill",
                    fill_value=0).reshape(num_experts, capacity, H)


def indexed_combine(expert_out, eids, pos, w, capacity):
    """(E,C,H) expert outputs -> (T,H) tokens: gather each (token,
    choice) slot and weighted-sum over the k choices (the reverse
    all_to_all, ~ global_gather_op.cu.cc)."""
    E, C, H = expert_out.shape
    flat = eids * capacity + pos  # (T, k)
    g = expert_out.reshape(E * C, H)[flat]  # (T, k, H)
    return jnp.sum(g * w[..., None].astype(expert_out.dtype), axis=-2)


def expert_choice_gating(logits, capacity):
    """Expert-choice routing (Zhou et al. 2022): each EXPERT picks its
    top-``capacity`` tokens instead of tokens picking experts. Load
    balance is exact by construction (every expert processes exactly C
    tokens), so there is no aux loss and no overflow dropping; a token
    may be picked by 0..E experts. Static shapes throughout — the
    top_k is over a fixed (E, T) score matrix, TPU-friendly.

    Returns (dispatch (T,E,C), combine (T,E,C), aux=0).
    """
    T, E = logits.shape
    capacity = min(capacity, T)  # top_k requires k <= T (tiny batches /
    #                              many experts; token-routing gates
    #                              tolerate cap > T but top_k raises)
    probs = jax.nn.softmax(logits, -1)           # per-token over experts
    g, idx = jax.lax.top_k(probs.T, capacity)    # (E, C): weights, tokens
    dispatch = jnp.transpose(
        jax.nn.one_hot(idx, T, dtype=jnp.float32), (2, 0, 1))  # (T,E,C)
    combine = dispatch * g[None, :, :]
    return dispatch, combine, jnp.zeros((), jnp.float32)


class BaseGate(nn.Layer):
    """~ gate/base_gate.py."""

    routing = "token"  # tokens pick experts (gshard/switch family)

    def __init__(self, d_model, num_experts):
        super().__init__()
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)
        self.num_experts = num_experts


class SwitchGate(BaseGate):
    top_k = 1


class GShardGate(BaseGate):
    top_k = 2


class NaiveGate(BaseGate):
    top_k = 2


class ExpertChoiceGate(BaseGate):
    """Experts pick tokens; top_k only feeds the capacity formula
    (C = top_k * capacity_factor * T / E)."""

    routing = "expert"
    top_k = 2


class MoELayer(nn.Layer):
    """~ moe_layer.py:233.

    experts: stacked FFN weights (E, d_model, d_hidden) / (E, d_hidden,
    d_model), expert dim annotated over the 'expert' mesh axis.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.25, top_k=None, group=None,
                 recompute_interval=0, dispatch_mode="indexed", name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        # "indexed" (default): scatter/gather dispatch, O(T*k*H) data
        # movement. "inverted": same math with the dispatch built by
        # int32 slot inversion + row gather (no H-wide scatter).
        # "einsum": the dense one-hot (T,E,C) formulation — O(T^2)
        # MACs, kept as the numerics oracle and for A/B benches.
        assert dispatch_mode in ("indexed", "inverted", "einsum"), \
            dispatch_mode
        self.dispatch_mode = dispatch_mode
        if isinstance(gate, str):
            gate_cls = {"gshard": GShardGate, "switch": SwitchGate,
                        "naive": NaiveGate,
                        "expert_choice": ExpertChoiceGate}[gate]
            self.gate = gate_cls(d_model, num_experts)
        else:
            self.gate = gate
        self.top_k = top_k or getattr(self.gate, "top_k", 2)

        from ..... import nn as _nn
        from .....nn import initializer as init
        self.w_in = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=init.XavierNormal())
        self.w_out = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=init.XavierNormal())
        self.w_in.sharding_spec = P("expert", None, "model")
        self.w_out.sharding_spec = P("expert", "model", None)
        self.aux_loss = None

    def capacity(self, num_tokens):
        cap = int(math.ceil(self.top_k * self.capacity_factor * num_tokens
                            / self.num_experts))
        return max(cap, 4)

    def forward(self, x):
        B, S, H = x.shape
        T = B * S
        cap = self.capacity(T)
        gate_logits = self.gate.wg(x)  # (B,S,E)
        topk = self.top_k
        key = _gen.next_key() if self.training else None

        routing = getattr(self.gate, "routing", "token")

        E = self.num_experts
        mode = self.dispatch_mode

        def expert_ffn(expert_in, w_in, w_out):
            h = jnp.einsum("ech,ehf->ecf", expert_in, w_in)
            h = jax.nn.gelu(h)
            return jnp.einsum("ecf,efh->ech", h, w_out)

        def fused(xv, gl, w_in, w_out):
            xt = xv.reshape(T, H)
            glt = gl.reshape(T, E).astype(jnp.float32)
            if routing == "expert":
                if mode == "indexed":
                    # experts pick tokens: the top_k already yields
                    # (E,C) token indices — dispatch is a plain gather,
                    # combine a scatter-add over picked tokens
                    c = min(cap, T)
                    probs = jax.nn.softmax(glt, -1)
                    g, idx = jax.lax.top_k(probs.T, c)  # (E,C)
                    expert_in = xt[idx]  # (E,C,H)
                    expert_out = expert_ffn(expert_in, w_in, w_out)
                    contrib = (g[..., None].astype(xt.dtype) * expert_out)
                    out = jnp.zeros((T, H), xt.dtype).at[
                        idx.reshape(-1)].add(contrib.reshape(E * c, H))
                    return (out.reshape(B, S, H),
                            jnp.zeros((), xt.dtype))
                dispatch, combine, aux = expert_choice_gating(glt, cap)
            elif mode in ("indexed", "inverted"):
                eids, pos, keep, w, aux = topk_gating_idx(
                    glt, cap, topk, key,
                    0.01 if (topk == 1 and key is not None) else 0.0)
                disp = (inverted_dispatch if mode == "inverted"
                        else indexed_dispatch)
                expert_in = disp(xt, eids, pos, keep, cap, E)
                expert_out = expert_ffn(expert_in, w_in, w_out)
                out = indexed_combine(expert_out, eids, pos, w, cap)
                return out.reshape(B, S, H), aux.astype(xt.dtype)
            elif topk == 1:
                dispatch, combine, aux = top1_gating(glt, cap, key,
                                                     0.01 if key is not None
                                                     else 0.0)
            elif topk == 2:
                dispatch, combine, aux = top2_gating(glt, cap)
            else:
                dispatch, combine, aux = topk_gating(glt, cap, topk)
            # (T,E,C) x (T,H) -> (E,C,H): the all_to_all boundary under SPMD
            expert_in = jnp.einsum("tec,th->ech",
                                   dispatch.astype(xt.dtype), xt)
            expert_out = expert_ffn(expert_in, w_in, w_out)
            out = jnp.einsum("tec,ech->th", combine.astype(xt.dtype),
                             expert_out)
            return out.reshape(B, S, H), aux.astype(xt.dtype)

        out, aux = apply_op("moe_layer", fused, x, gate_logits, self.w_in,
                            self.w_out)
        self.aux_loss = aux
        return out
