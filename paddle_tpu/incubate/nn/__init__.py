"""Fused transformer layers.

~ python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention:39, FusedFeedForward:230, FusedMultiTransformer:627
backed by CUDA fused_attention_op/fused_feedforward_op). On TPU "fused"
means: one jitted region; attention uses the Pallas flash kernel; XLA fuses
bias/dropout/residual/layernorm into the surrounding matmuls.
"""
from __future__ import annotations

from ... import nn
from ...nn import functional as F


class FusedMultiHeadAttention(nn.Layer):
    """~ fused_transformer.py:39 (pre/post-LN + attention + residual)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr
                 =None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.attn = nn.MultiHeadAttention(embed_dim, num_heads,
                                          attn_dropout_rate)
        self.dropout = nn.Dropout(dropout_rate)
        self.ln_pre = nn.LayerNorm(embed_dim, epsilon)
        self.ln_post = nn.LayerNorm(embed_dim, epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        if self.normalize_before:
            query = self.ln_pre(query)
        out = self.attn(query, key, value, attn_mask=attn_mask, cache=cache)
        if isinstance(out, tuple):
            out = out[0]
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln_post(out)
        return out


class FusedFeedForward(nn.Layer):
    """~ fused_transformer.py:230."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 linear1_weight_attr, linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 linear2_weight_attr, linear2_bias_attr)
        self.dropout1 = nn.Dropout(act_dropout_rate if act_dropout_rate
                                   is not None else dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.norm = nn.LayerNorm(d_model, epsilon)
        self.activation = getattr(F, activation)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.linear2(self.dropout1(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm(src)
        return src


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedLinear(nn.Linear):
    pass
