"""Fused transformer layers.

~ python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention:39, FusedFeedForward:230, FusedMultiTransformer:627
backed by CUDA fused_attention_op/fused_feedforward_op). On TPU "fused"
means: the residual epilogue ``ln(residual + dropout(x))`` runs the Pallas
dropout-add-layernorm kernel (one VMEM pass, differentiable custom VJP —
the fused_bias_dropout_residual_layer_norm analog); attention rides the
Pallas flash kernel where eligible; XLA fuses the rest into the matmuls.
"""
from __future__ import annotations

from ... import nn
from ...core.tensor import Tensor
from ...nn import functional as F
from ...ops.dispatch import apply_op


def _fused_epilogue(x, residual, ln: "nn.LayerNorm", p: float,
                    training: bool):
    """ln(residual + dropout(x)) through the Pallas fused kernel."""
    from ...ops.pallas.dropout_ln import fused_dropout_add_layer_norm

    def fn(xv, rv, wv, bv):
        return fused_dropout_add_layer_norm(
            xv, rv, wv, bv, p=p, eps=ln.epsilon, training=training)

    return apply_op("fused_dropout_add_ln", fn, x, residual,
                    ln.weight, ln.bias)


class FusedMultiHeadAttention(nn.Layer):
    """~ fused_transformer.py:39 (pre/post-LN + attention + residual)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr
                 =None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.attn = nn.MultiHeadAttention(embed_dim, num_heads,
                                          attn_dropout_rate)
        self.dropout = nn.Dropout(dropout_rate)
        self.ln_pre = nn.LayerNorm(embed_dim, epsilon)
        self.ln_post = nn.LayerNorm(embed_dim, epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        if self.normalize_before:
            query = self.ln_pre(query)
        out = self.attn(query, key, value, attn_mask=attn_mask, cache=cache)
        if isinstance(out, tuple):
            out = out[0]
        if not self.normalize_before:
            # post-LN epilogue in one fused VMEM pass
            return _fused_epilogue(out, residual, self.ln_post,
                                   self.dropout.p, self.training)
        return residual + self.dropout(out)


class FusedFeedForward(nn.Layer):
    """~ fused_transformer.py:230."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 linear1_weight_attr, linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 linear2_weight_attr, linear2_bias_attr)
        self.dropout1 = nn.Dropout(act_dropout_rate if act_dropout_rate
                                   is not None else dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.norm = nn.LayerNorm(d_model, epsilon)
        self.activation = getattr(F, activation)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.linear2(self.dropout1(self.activation(self.linear1(src))))
        if not self.normalize_before:
            return _fused_epilogue(src, residual, self.norm,
                                   self.dropout2.p, self.training)
        return residual + self.dropout2(src)


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedLinear(nn.Linear):
    pass


class FusedMultiTransformer(nn.Layer):
    """~ fused_transformer.py FusedMultiTransformer:627
    (operators/fused/fused_multi_transformer_op.cu): the whole decoder
    stack as ONE op with stacked per-layer weights and an in-place KV
    cache — the reference's flagship generative-inference kernel.

    TPU-native form: per-layer weights are stacked on a leading axis and a
    ``lax.scan`` walks the stack — one compiled region, weights resident,
    zero per-layer dispatch — with a functional (batch, 2, heads, T, d)
    KV cache threaded through for incremental decoding.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        import numpy as np
        import jax
        import jax.numpy as jnp
        from ...core.generator import default_generator
        from ...core.tensor import Parameter
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.dim_feedforward = dim_feedforward
        self.epsilon = epsilon
        self.activation = activation
        self.normalize_before = normalize_before

        def init(shape, fan_in):
            limit = float(np.sqrt(6.0 / max(1, fan_in)))
            return jax.random.uniform(default_generator().next_key(),
                                      shape, jnp.float32, -limit, limit)

        L, D, Fd = num_layers, embed_dim, dim_feedforward
        self.qkv_weight = Parameter(init((L, D, 3 * D), D))
        self.qkv_bias = Parameter(jnp.zeros((L, 3 * D)))
        self.out_weight = Parameter(init((L, D, D), D))
        self.out_bias = Parameter(jnp.zeros((L, D)))
        self.ffn1_weight = Parameter(init((L, D, Fd), D))
        self.ffn1_bias = Parameter(jnp.zeros((L, Fd)))
        self.ffn2_weight = Parameter(init((L, Fd, D), Fd))
        self.ffn2_bias = Parameter(jnp.zeros((L, D)))
        self.ln_scale = Parameter(jnp.ones((L, D)))
        self.ln_bias = Parameter(jnp.zeros((L, D)))
        self.ffn_ln_scale = Parameter(jnp.ones((L, D)))
        self.ffn_ln_bias = Parameter(jnp.zeros((L, D)))

    def gen_cache(self, batch_size, max_len):
        """Empty stacked KV cache: (L, B, 2, H, max_len, hd)."""
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        return Tensor(jnp.zeros((self.num_layers, batch_size, 2,
                                 self.num_heads, max_len, self.head_dim)))

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        import jax
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        H, hd, eps = self.num_heads, self.head_dim, self.epsilon
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[self.activation]
        pre_ln = self.normalize_before
        t_step = None if time_step is None else int(time_step)

        def ln(x, scale, bias):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.mean(jnp.square(x - mu), -1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + eps) * scale + bias

        def fn(x, qkv_w, qkv_b, out_w, out_b, f1w, f1b, f2w, f2b,
               lns, lnb, flns, flnb, *rest):
            mask = None
            cache = None
            ri = 0
            if attn_mask is not None:
                mask = rest[ri]
                ri += 1
            if caches is not None:
                cache = rest[ri]
            B, T, D = x.shape

            def layer(carry, wl):
                h, cache_l_acc = carry
                (qkv_wl, qkv_bl, out_wl, out_bl, f1wl, f1bl, f2wl, f2bl,
                 lnsl, lnbl, flnsl, flnbl, cache_l, li) = wl
                resid = h
                hin = ln(h, lnsl, lnbl) if pre_ln else h
                qkv = hin @ qkv_wl + qkv_bl            # (B, T, 3D)
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(z):
                    return z.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
                q, k, v = heads(q), heads(k), heads(v)
                if cache_l is not None and t_step is not None:
                    # incremental decode: append this step's K/V at t_step
                    k_full = jax.lax.dynamic_update_slice(
                        cache_l[:, 0], k, (0, 0, t_step, 0))
                    v_full = jax.lax.dynamic_update_slice(
                        cache_l[:, 1], v, (0, 0, t_step, 0))
                    new_cache_l = jnp.stack([k_full, v_full], 1)
                    kv_len = t_step + T
                    k_use = k_full[:, :, :, :]
                    v_use = v_full[:, :, :, :]
                    key_mask = (jnp.arange(k_full.shape[2])
                                < kv_len)[None, None, None, :]
                else:
                    new_cache_l = cache_l if cache_l is not None else 0.0
                    k_use, v_use = k, v
                    key_mask = None
                scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_use) \
                    / jnp.sqrt(jnp.asarray(hd, x.dtype))
                neg = jnp.finfo(x.dtype).min
                if key_mask is not None:
                    scores = jnp.where(key_mask, scores, neg)
                elif mask is not None:
                    scores = scores + mask
                else:
                    cm = jnp.tril(jnp.ones((T, k_use.shape[2]), bool))
                    scores = jnp.where(cm, scores, neg)
                probs = jax.nn.softmax(scores, -1)
                attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v_use)
                attn = attn.transpose(0, 2, 1, 3).reshape(B, T, D)
                h = resid + attn @ out_wl + out_bl
                if not pre_ln:
                    h = ln(h, lnsl, lnbl)
                resid = h
                hin = ln(h, flnsl, flnbl) if pre_ln else h
                h = resid + act(hin @ f1wl + f1bl) @ f2wl + f2bl
                if not pre_ln:
                    h = ln(h, flnsl, flnbl)
                return (h, cache_l_acc), new_cache_l

            L = self.num_layers
            cache_stack = cache if cache is not None else \
                jnp.zeros((L, 0, 0, 0, 0, 0), x.dtype)
            xs = (qkv_w, qkv_b, out_w, out_b, f1w, f1b, f2w, f2b,
                  lns, lnb, flns, flnb,
                  cache_stack if cache is not None else jnp.zeros((L, 1)),
                  jnp.arange(L))
            if cache is not None:
                (h, _), new_caches = jax.lax.scan(
                    lambda c, wl: layer(c, wl), (x, 0.0), xs)
                return h, new_caches
            # no cache: scan without emitting caches
            def layer_nc(h, wl):
                (h2, _), _ = layer((h, 0.0), wl[:12] + (None, wl[13]))
                return h2, None
            h, _ = jax.lax.scan(layer_nc, x, xs)
            return h

        args = [src, self.qkv_weight, self.qkv_bias, self.out_weight,
                self.out_bias, self.ffn1_weight, self.ffn1_bias,
                self.ffn2_weight, self.ffn2_bias, self.ln_scale,
                self.ln_bias, self.ffn_ln_scale, self.ffn_ln_bias]
        if attn_mask is not None:
            args.append(attn_mask)
        if caches is not None:
            args.append(caches)
        return apply_op("fused_multi_transformer", fn, *args)
