from . import auto_checkpoint  # noqa: F401
from .auto_checkpoint import (CheckpointSaver, ExeTrainStatus,  # noqa: F401
                              PreemptionGuard, train_epoch_range)
