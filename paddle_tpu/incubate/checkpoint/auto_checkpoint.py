"""Auto-checkpoint: job-keyed epoch-range training with transparent resume.

~ python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71 (epoch
range generator :598, ExeTrainStatus :193, save_checkpoint :458) +
checkpoint_saver.py:53 — the reference checkpoints to HDFS keyed by
PADDLE_JOB_ID and, on restart, `train_epoch_range` silently skips the
epochs that already ran. Same contract here over the fs abstraction
(LocalFS default, HDFSClient when PADDLE_CHECKPOINT_FS=hdfs), with
atomic tmp-dir renames and bounded history (max_ckpt_nums analog).

Usage::

    for epoch in train_epoch_range(10, model=model, optimizer=opt):
        ...train one epoch...
    # on restart with the same PADDLE_JOB_ID + checkpoint dir, completed
    # epochs are skipped and model/optimizer state is restored.
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

from ...distributed.fleet.utils.fs import FS, LocalFS


def _job_id() -> str:
    return os.environ.get("PADDLE_JOB_ID", "default_job")


def _root_dir() -> str:
    return os.environ.get("PADDLE_AUTO_CHECKPOINT_DIR",
                          "./auto_checkpoint")


def _enabled() -> bool:
    return os.environ.get("PADDLE_ENABLE_AUTO_CHECKPOINT", "1") != "0"


class ExeTrainStatus:
    """Serializable training progress (~ auto_checkpoint.py:193)."""

    def __init__(self, epoch_no: int = -1, checkpoint_no: int = 0):
        self.epoch_no = epoch_no
        self.checkpoint_no = checkpoint_no

    def to_dict(self):
        return {"epoch_no": self.epoch_no,
                "checkpoint_no": self.checkpoint_no,
                "timestamp": time.time()}

    @classmethod
    def from_dict(cls, d):
        return cls(int(d["epoch_no"]), int(d.get("checkpoint_no", 0)))


class CheckpointSaver:
    """Versioned checkpoint directory manager (~ checkpoint_saver.py:53).

    Layout: <root>/<job_id>/ckpt_<n>/ containing `state.pdparams`
    (model+optimizer state via framework io) and `meta.json`
    (ExeTrainStatus). Saves go to a tmp dir then mv — readers never see a
    torn checkpoint. Keeps the newest ``max_ckpt_nums``.
    """

    def __init__(self, fs: Optional[FS] = None, root: Optional[str] = None,
                 job_id: Optional[str] = None, max_ckpt_nums: int = 3):
        self.fs = fs or LocalFS()
        self.root = root or _root_dir()
        self.job_id = job_id or _job_id()
        self.max_ckpt_nums = max_ckpt_nums

    @property
    def job_dir(self) -> str:
        return f"{self.root}/{self.job_id}"

    def _ckpt_nos(self):
        dirs, _ = self.fs.ls_dir(self.job_dir)
        nos = []
        for d in dirs:
            if d.startswith("ckpt_") and d[5:].isdigit():
                nos.append(int(d[5:]))
        return sorted(nos)

    def save_checkpoint(self, state_bytes: bytes, status: ExeTrainStatus,
                        local_cache_path: str = ".ckpt_cache") -> int:
        nos = self._ckpt_nos()
        no = (nos[-1] + 1) if nos else 0
        status.checkpoint_no = no
        final = f"{self.job_dir}/ckpt_{no}"
        tmp = f"{self.job_dir}/.tmp_ckpt_{no}_{os.getpid()}"
        if self.fs.need_upload_download():
            os.makedirs(local_cache_path, exist_ok=True)
            sp = os.path.join(local_cache_path, f"state_{no}")
            with open(sp, "wb") as f:
                f.write(state_bytes)
            mp = os.path.join(local_cache_path, f"meta_{no}.json")
            with open(mp, "w") as f:
                json.dump(status.to_dict(), f)
            self.fs.mkdirs(tmp)
            self.fs.upload(sp, f"{tmp}/state.pdparams")
            self.fs.upload(mp, f"{tmp}/meta.json")
            os.remove(sp)
            os.remove(mp)
        else:
            self.fs.mkdirs(tmp)
            with open(f"{tmp}/state.pdparams", "wb") as f:
                f.write(state_bytes)
            with open(f"{tmp}/meta.json", "w") as f:
                json.dump(status.to_dict(), f)
        self.fs.mv(tmp, final, overwrite=True)
        self._gc()
        return no

    def load_checkpoint(self, ckpt_no: Optional[int] = None,
                        local_cache_path: str = ".ckpt_cache"):
        """Returns (state_bytes, ExeTrainStatus) or (None, None)."""
        nos = self._ckpt_nos()
        if not nos:
            return None, None
        no = nos[-1] if ckpt_no is None else ckpt_no
        d = f"{self.job_dir}/ckpt_{no}"
        try:
            meta = json.loads(self.fs.cat(f"{d}/meta.json"))
        except (ValueError, OSError):
            return None, None
        if self.fs.need_upload_download():
            os.makedirs(local_cache_path, exist_ok=True)
            lp = os.path.join(local_cache_path, f"load_{no}")
            self.fs.download(f"{d}/state.pdparams", lp)
            with open(lp, "rb") as f:
                blob = f.read()
            os.remove(lp)
        else:
            with open(f"{d}/state.pdparams", "rb") as f:
                blob = f.read()
        return blob, ExeTrainStatus.from_dict(meta)

    def _gc(self):
        nos = self._ckpt_nos()
        for no in nos[:-self.max_ckpt_nums]:
            self.fs.delete(f"{self.job_dir}/ckpt_{no}")


def _to_numpy_tree(tree):
    import jax
    import numpy as np

    from ...core.tensor import Tensor
    return jax.tree.map(
        lambda x: np.asarray(x._value) if isinstance(x, Tensor) else x,
        tree, is_leaf=lambda x: isinstance(x, Tensor))


def _to_tensor_tree(tree):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ...core.tensor import Tensor
    return jax.tree.map(
        lambda x: Tensor(jnp.asarray(x)) if isinstance(x, np.ndarray)
        else x, tree)


def _pack_state(model, optimizer) -> bytes:
    import pickle
    state = {}
    if model is not None:
        state["model"] = _to_numpy_tree(dict(model.state_dict()))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        state["opt"] = _to_numpy_tree(optimizer.state_dict())
    return pickle.dumps(state, protocol=4)


def _unpack_state(blob: bytes, model, optimizer):
    import pickle
    state = pickle.loads(blob)
    if model is not None and "model" in state:
        model.set_state_dict(_to_tensor_tree(state["model"]))
    if optimizer is not None and "opt" in state and \
            hasattr(optimizer, "set_state_dict"):
        optimizer.set_state_dict(_to_tensor_tree(state["opt"]))


class PreemptionGuard:
    """SIGTERM-aware training guard for preemptible TPU pods.

    Cloud TPU preemption delivers SIGTERM with a grace window; the
    reference's trainers rely on external checkpoint cadence instead
    (incubate/checkpoint/auto_checkpoint.py has no signal path). Here
    the guard flips a flag on SIGTERM/SIGINT so the training loop can
    save at the next step boundary and exit cleanly:

        with PreemptionGuard() as guard:
            for epoch in train_epoch_range(100, model, opt, guard=guard):
                ...train...
        # on SIGTERM: state saved, loop ends; relaunch resumes the epoch

    The previous handler is chained (a second signal still kills the
    process through it) and restored on __exit__.
    """

    def __init__(self, signals=None):
        import signal as _sig
        self._sig = _sig
        self.signals = tuple(signals or (_sig.SIGTERM, _sig.SIGINT))
        self.preempted = False
        self._prev = {}

    def _handler(self, signum, frame):
        self.preempted = True
        prev = self._prev.get(signum)
        # restore the previous handler so a second signal is fatal
        self._sig.signal(signum, prev if callable(prev)
                         else self._sig.SIG_DFL)

    def __enter__(self):
        for s in self.signals:
            self._prev[s] = self._sig.signal(s, self._handler)
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            try:
                self._sig.signal(s, prev)
            except (ValueError, TypeError):
                pass
        return False


def train_epoch_range(max_epoch_num: int, model=None, optimizer=None,
                      save_checkpoint_inter: int = 1,
                      saver: Optional[CheckpointSaver] = None,
                      guard: Optional[PreemptionGuard] = None
                      ) -> Iterator[int]:
    """Epoch generator with transparent resume (~ auto_checkpoint.py:598).

    Yields epoch numbers that still need to run; after each yielded epoch
    (every ``save_checkpoint_inter`` epochs) the model+optimizer state is
    checkpointed. On restart under the same job id, already-completed
    epochs are skipped and state is restored before the first yield.
    With a ``guard`` (PreemptionGuard), a SIGTERM during an epoch saves
    that epoch's state and ends the loop at the boundary — the relaunch
    resumes from the next epoch.
    """
    if not _enabled():
        for epoch in range(max_epoch_num):
            yield epoch
            if guard is not None and guard.preempted:
                return
        return
    saver = saver or CheckpointSaver()
    start = 0
    blob, status = saver.load_checkpoint()
    if status is not None:
        start = status.epoch_no + 1
        if blob is not None:
            _unpack_state(blob, model, optimizer)
    for epoch in range(start, max_epoch_num):
        yield epoch
        preempted = guard is not None and guard.preempted
        if preempted or (epoch - start) % max(1, save_checkpoint_inter) \
                == 0 or epoch == max_epoch_num - 1:
            saver.save_checkpoint(_pack_state(model, optimizer),
                                  ExeTrainStatus(epoch_no=epoch))
        if preempted:
            return
