"""StringTensor + strings kernels + FasterTokenizer analog.

~ paddle/phi/core/string_tensor.h (pstring array tensor) and
phi/kernels/strings/strings_lower_upper_kernel.h (+ unicode.h case
tables); tokenizer ~ the faster_tokenizer op family
(test_faster_tokenizer_op.py surface). TPU-native split: strings live on
the host as numpy object arrays (device tensors are numeric by
definition on XLA); the tokenizer's OUTPUT (ids/type-ids padded arrays)
is what crosses onto the device. Case mapping uses Python's full Unicode
tables — the role phi/kernels/strings/unicode.h plays in C++.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "lower", "upper",
           "FasterTokenizer", "BasicTokenizer", "WordpieceTokenizer"]


class StringTensor:
    """Host-resident string array (~ phi::StringTensor)."""

    def __init__(self, data: Union[Sequence[str], np.ndarray],
                 name: str = ""):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self.tolist()!r})"


def to_string_tensor(strings: Sequence[str]) -> StringTensor:
    return StringTensor(strings)


def _elementwise(st, fn):
    data = st._data if isinstance(st, StringTensor) else np.asarray(
        st, dtype=object)
    return StringTensor(np.vectorize(fn, otypes=[object])(data))


def lower(x, use_utf8_encoding: bool = True) -> StringTensor:
    """~ strings_lower_upper_kernel.h StringLowerKernel."""
    return _elementwise(x, lambda s: s.lower())


def upper(x, use_utf8_encoding: bool = True) -> StringTensor:
    """~ strings_lower_upper_kernel.h StringUpperKernel."""
    return _elementwise(x, lambda s: s.upper())


# ---------------------------------------------------------------------------
# tokenizer (faster_tokenizer analog)
# ---------------------------------------------------------------------------
def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    import unicodedata
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(ch: str) -> bool:
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF)


class BasicTokenizer:
    """Whitespace + punctuation + CJK splitting (BERT basic tokenizer —
    the first stage of the reference faster_tokenizer pipeline)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        import unicodedata
        if self.do_lower_case:
            text = text.lower()
            text = "".join(c for c in unicodedata.normalize("NFD", text)
                           if unicodedata.category(c) != "Mn")
        out: List[str] = []
        buf = []
        for ch in text:
            if ch.isspace():
                if buf:
                    out.append("".join(buf))
                    buf = []
            elif _is_punctuation(ch) or _is_chinese_char(ch):
                if buf:
                    out.append("".join(buf))
                    buf = []
                out.append(ch)
            else:
                buf.append(ch)
        if buf:
            out.append("".join(buf))
        return out


class WordpieceTokenizer:
    """Greedy longest-match-first subword splitting (BERT wordpiece —
    second stage of the faster_tokenizer pipeline)."""

    def __init__(self, vocab: dict, unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        tokens = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class FasterTokenizer:
    """~ the faster_tokenizer op (test_faster_tokenizer_op.py surface):
    text (+ optional text pair) -> padded input_ids / token_type_ids
    numpy arrays ready for device transfer."""

    def __init__(self, vocab: dict, do_lower_case: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]"):
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.cls_id = vocab[cls_token]
        self.sep_id = vocab[sep_token]
        self.pad_id = vocab.get(pad_token, 0)
        # native fast path bookkeeping: the C map stores piece -> ROW
        # (insertion index); rows translate back through _row_to_id
        self._pieces = list(vocab)
        self._row_to_id = np.asarray([vocab[p] for p in self._pieces],
                                     np.int64)
        self._unk_row = (self._pieces.index(unk_token)
                         if unk_token in vocab else 0)
        self._native = None  # lazy: False (unavailable) or (lib, handle)

    def _native_handle(self):
        """Build the C vocab once (~ faster_tokenizer's C++ core). The
        native path covers pure-ASCII texts; others fall back per-text
        to the Python pipeline (which owns unicode/CJK)."""
        if self._native is None:
            import ctypes

            from ..utils import native as _nat
            lib = _nat.get_lib()
            if lib is None or not hasattr(lib, "wp_new"):
                self._native = False
            else:
                blob = "".join(self._pieces).encode("utf-8")
                offs = np.zeros(len(self._pieces) + 1, np.int32)
                np.cumsum([len(p.encode("utf-8")) for p in self._pieces],
                          out=offs[1:])
                handle = lib.wp_new(
                    blob,
                    offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    len(self._pieces))
                import weakref
                weakref.finalize(self, lib.wp_free, handle)
                self._native = (lib, handle)
        return self._native

    # texts longer than this go to Python (keeps the per-row output
    # buffer, n x 2*longest, bounded for mixed batches)
    _NATIVE_MAX_TEXT_BYTES = 4096

    def _encode_batch_native(self, texts):
        """Returns list[list[int] | None] (None = needs Python path)."""
        nat = self._native_handle()
        if not nat or not texts:
            return [None] * len(texts)
        import ctypes
        lib, handle = nat
        enc_all = [t.encode("utf-8") for t in texts]
        keep = [i for i, e in enumerate(enc_all)
                if len(e) <= self._NATIVE_MAX_TEXT_BYTES]
        out: list = [None] * len(texts)
        if not keep or sum(len(enc_all[i]) for i in keep) >= 2**31:
            return out  # int32 offsets can't address the blob
        enc = [enc_all[i] for i in keep]
        blob = b"".join(enc)
        offs = np.zeros(len(enc) + 1, np.int32)
        np.cumsum([len(e) for e in enc], out=offs[1:])
        max_out = 2 * max(len(e) for e in enc) + 8
        ids = np.empty((len(enc), max_out), np.int32)
        lens = np.empty(len(enc), np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.wp_encode(handle, blob, offs.ctypes.data_as(i32p), len(enc),
                      self._unk_row, self.wordpiece.max_chars,
                      int(self.basic.do_lower_case),
                      ids.ctypes.data_as(i32p), lens.ctypes.data_as(i32p),
                      max_out)
        for r, i in enumerate(keep):
            if lens[r] >= 0:
                out[i] = self._row_to_id[ids[r, :lens[r]]].tolist()
        return out

    def _encode_one(self, text: str) -> List[int]:
        ids = []
        for w in self.basic.tokenize(text):
            for piece in self.wordpiece.tokenize(w):
                ids.append(self.vocab[piece])
        return ids

    def __call__(self, text, text_pair=None, max_seq_len: int = 0,
                 pad_to_max_seq_len: bool = False):
        texts = (text.tolist() if isinstance(text, StringTensor)
                 else list(text))
        pairs = None
        if text_pair is not None:
            pairs = (text_pair.tolist()
                     if isinstance(text_pair, StringTensor)
                     else list(text_pair))
        fast = self._encode_batch_native(texts)
        fast_pairs = (self._encode_batch_native(pairs)
                      if pairs is not None else None)
        all_ids, all_types = [], []
        for i, t in enumerate(texts):
            body = fast[i] if fast[i] is not None else self._encode_one(t)
            ids = [self.cls_id] + body + [self.sep_id]
            types = [0] * len(ids)
            if pairs is not None:
                pbody = (fast_pairs[i] if fast_pairs[i] is not None
                         else self._encode_one(pairs[i]))
                pids = pbody + [self.sep_id]
                ids += pids
                types += [1] * len(pids)
            if max_seq_len and len(ids) > max_seq_len:
                ids = ids[:max_seq_len - 1] + [self.sep_id]
                types = types[:max_seq_len]
            all_ids.append(ids)
            all_types.append(types)
        width = max(len(i) for i in all_ids)
        if pad_to_max_seq_len and max_seq_len:
            width = max_seq_len
        input_ids = np.full((len(all_ids), width), self.pad_id, np.int64)
        token_type = np.zeros((len(all_ids), width), np.int64)
        for r, (ids, types) in enumerate(zip(all_ids, all_types)):
            input_ids[r, :len(ids)] = ids
            token_type[r, :len(types)] = types
        return input_ids, token_type
