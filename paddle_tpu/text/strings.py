"""StringTensor + strings kernels + FasterTokenizer analog.

~ paddle/phi/core/string_tensor.h (pstring array tensor) and
phi/kernels/strings/strings_lower_upper_kernel.h (+ unicode.h case
tables); tokenizer ~ the faster_tokenizer op family
(test_faster_tokenizer_op.py surface). TPU-native split: strings live on
the host as numpy object arrays (device tensors are numeric by
definition on XLA); the tokenizer's OUTPUT (ids/type-ids padded arrays)
is what crosses onto the device. Case mapping uses Python's full Unicode
tables — the role phi/kernels/strings/unicode.h plays in C++.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "lower", "upper",
           "FasterTokenizer", "BasicTokenizer", "WordpieceTokenizer"]


class StringTensor:
    """Host-resident string array (~ phi::StringTensor)."""

    def __init__(self, data: Union[Sequence[str], np.ndarray],
                 name: str = ""):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self.tolist()!r})"


def to_string_tensor(strings: Sequence[str]) -> StringTensor:
    return StringTensor(strings)


def _elementwise(st, fn):
    data = st._data if isinstance(st, StringTensor) else np.asarray(
        st, dtype=object)
    return StringTensor(np.vectorize(fn, otypes=[object])(data))


def lower(x, use_utf8_encoding: bool = True) -> StringTensor:
    """~ strings_lower_upper_kernel.h StringLowerKernel."""
    return _elementwise(x, lambda s: s.lower())


def upper(x, use_utf8_encoding: bool = True) -> StringTensor:
    """~ strings_lower_upper_kernel.h StringUpperKernel."""
    return _elementwise(x, lambda s: s.upper())


# ---------------------------------------------------------------------------
# tokenizer (faster_tokenizer analog)
# ---------------------------------------------------------------------------
def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    import unicodedata
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(ch: str) -> bool:
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF)


class BasicTokenizer:
    """Whitespace + punctuation + CJK splitting (BERT basic tokenizer —
    the first stage of the reference faster_tokenizer pipeline)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        import unicodedata
        if self.do_lower_case:
            text = text.lower()
            text = "".join(c for c in unicodedata.normalize("NFD", text)
                           if unicodedata.category(c) != "Mn")
        out: List[str] = []
        buf = []
        for ch in text:
            if ch.isspace():
                if buf:
                    out.append("".join(buf))
                    buf = []
            elif _is_punctuation(ch) or _is_chinese_char(ch):
                if buf:
                    out.append("".join(buf))
                    buf = []
                out.append(ch)
            else:
                buf.append(ch)
        if buf:
            out.append("".join(buf))
        return out


class WordpieceTokenizer:
    """Greedy longest-match-first subword splitting (BERT wordpiece —
    second stage of the faster_tokenizer pipeline)."""

    def __init__(self, vocab: dict, unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        tokens = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class FasterTokenizer:
    """~ the faster_tokenizer op (test_faster_tokenizer_op.py surface):
    text (+ optional text pair) -> padded input_ids / token_type_ids
    numpy arrays ready for device transfer."""

    def __init__(self, vocab: dict, do_lower_case: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]"):
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.cls_id = vocab[cls_token]
        self.sep_id = vocab[sep_token]
        self.pad_id = vocab.get(pad_token, 0)

    def _encode_one(self, text: str) -> List[int]:
        ids = []
        for w in self.basic.tokenize(text):
            for piece in self.wordpiece.tokenize(w):
                ids.append(self.vocab[piece])
        return ids

    def __call__(self, text, text_pair=None, max_seq_len: int = 0,
                 pad_to_max_seq_len: bool = False):
        texts = (text.tolist() if isinstance(text, StringTensor)
                 else list(text))
        pairs = None
        if text_pair is not None:
            pairs = (text_pair.tolist()
                     if isinstance(text_pair, StringTensor)
                     else list(text_pair))
        all_ids, all_types = [], []
        for i, t in enumerate(texts):
            ids = [self.cls_id] + self._encode_one(t) + [self.sep_id]
            types = [0] * len(ids)
            if pairs is not None:
                pids = self._encode_one(pairs[i]) + [self.sep_id]
                ids += pids
                types += [1] * len(pids)
            if max_seq_len and len(ids) > max_seq_len:
                ids = ids[:max_seq_len - 1] + [self.sep_id]
                types = types[:max_seq_len]
            all_ids.append(ids)
            all_types.append(types)
        width = max(len(i) for i in all_ids)
        if pad_to_max_seq_len and max_seq_len:
            width = max_seq_len
        input_ids = np.full((len(all_ids), width), self.pad_id, np.int64)
        token_type = np.zeros((len(all_ids), width), np.int64)
        for r, (ids, types) in enumerate(zip(all_ids, all_types)):
            input_ids[r, :len(ids)] = ids
            token_type[r, :len(types)] = types
        return input_ids, token_type
