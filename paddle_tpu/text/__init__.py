"""paddle_tpu.text — text datasets + sequence decoding ops.

~ python/paddle/text/ (datasets: Imdb/Conll05/Movielens/UCIHousing/WMT14/
WMT16 — file-backed with synthetic fallback for the zero-egress env) and
the viterbi_decode op (paddle.text.viterbi_decode over phi viterbi kernel).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..ops.dispatch import apply_op


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF viterbi decoding via lax.scan (phi viterbi_decode analog).

    potentials: (B, T, N) emission scores; transition_params: (N, N).
    Returns (scores (B,), paths (B, T)).
    """
    def fn(emis, trans):
        B, T, N = emis.shape

        def step(carry, e_t):
            score = carry  # (B, N)
            # score[b, j] = max_i score[b, i] + trans[i, j] + e_t[b, j]
            total = score[:, :, None] + trans[None]  # (B, N, N)
            best = jnp.max(total, axis=1) + e_t
            idx = jnp.argmax(total, axis=1)  # (B, N)
            return best, idx

        init = emis[:, 0]
        scores, backptrs = jax.lax.scan(
            step, init, jnp.swapaxes(emis[:, 1:], 0, 1))
        final_score = jnp.max(scores, -1)
        last = jnp.argmax(scores, -1)  # (B,)

        def back(carry, ptr_t):
            cur = carry
            prev = jnp.take_along_axis(ptr_t, cur[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(back, last, backptrs, reverse=True)
        paths = jnp.concatenate(
            [jnp.swapaxes(path_rev, 0, 1), last[:, None]], axis=1)
        return final_score, paths.astype(jnp.int64)
    return apply_op("viterbi_decode", fn, potentials, transition_params)


class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic fallback for text datasets (zero egress)."""

    def __init__(self, n, seq_len, vocab, n_classes, seed):
        rng = np.random.default_rng(seed)
        self.x = rng.integers(1, vocab, (n, seq_len)).astype(np.int64)
        # label correlated with token sum so models can learn
        self.y = ((self.x.sum(-1) // seq_len) % n_classes).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class Imdb(_SyntheticTextDataset):
    """~ text/datasets/imdb.py; reads local copy if present else synthetic."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/imdb.npz")
        if os.path.exists(local):
            d = np.load(local)
            self.x = d[f"x_{mode}"]
            self.y = d[f"y_{mode}"]
        else:
            super().__init__(5000 if mode == "train" else 1000, 128, 5000, 2,
                             seed=0 if mode == "train" else 1)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/housing.data")
        if os.path.exists(local):
            raw = np.loadtxt(local).astype(np.float32)
        else:
            rng = np.random.default_rng(0)
            feats = rng.standard_normal((506, 13)).astype(np.float32)
            w = rng.standard_normal(13).astype(np.float32)
            target = feats @ w + 0.1 * rng.standard_normal(506).astype(
                np.float32)
            raw = np.concatenate([feats, target[:, None]], 1)
        split = int(0.8 * len(raw))
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, i):
        return self.data[i, :-1], self.data[i, -1:]

    def __len__(self):
        return len(self.data)


class Conll05st(_SyntheticTextDataset):
    def __init__(self, data_file=None, mode="train", **kw):
        super().__init__(2000, 64, 8000, 20, seed=2)


class Movielens(_SyntheticTextDataset):
    def __init__(self, data_file=None, mode="train", **kw):
        super().__init__(4000, 16, 4000, 5, seed=3)
