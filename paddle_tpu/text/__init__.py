"""paddle_tpu.text — text datasets + sequence decoding ops.

~ python/paddle/text/ (datasets: Imdb/Conll05/Movielens/UCIHousing/WMT14/
WMT16 — file-backed with synthetic fallback for the zero-egress env) and
the viterbi_decode op (paddle.text.viterbi_decode over phi viterbi kernel).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..ops.dispatch import apply_op


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF viterbi decoding via lax.scan (phi viterbi_decode analog).

    potentials: (B, T, N) emission scores; transition_params: (N, N).
    Returns (scores (B,), paths (B, T)).
    """
    def fn(emis, trans):
        B, T, N = emis.shape

        def step(carry, e_t):
            score = carry  # (B, N)
            # score[b, j] = max_i score[b, i] + trans[i, j] + e_t[b, j]
            total = score[:, :, None] + trans[None]  # (B, N, N)
            best = jnp.max(total, axis=1) + e_t
            idx = jnp.argmax(total, axis=1)  # (B, N)
            return best, idx

        init = emis[:, 0]
        scores, backptrs = jax.lax.scan(
            step, init, jnp.swapaxes(emis[:, 1:], 0, 1))
        final_score = jnp.max(scores, -1)
        last = jnp.argmax(scores, -1)  # (B,)

        def back(carry, ptr_t):
            cur = carry
            prev = jnp.take_along_axis(ptr_t, cur[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(back, last, backptrs, reverse=True)
        paths = jnp.concatenate(
            [jnp.swapaxes(path_rev, 0, 1), last[:, None]], axis=1)
        return final_score, paths.astype(jnp.int64)
    return apply_op("viterbi_decode", fn, potentials, transition_params)


class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic fallback for text datasets (zero egress)."""

    def __init__(self, n, seq_len, vocab, n_classes, seed):
        rng = np.random.default_rng(seed)
        self.x = rng.integers(1, vocab, (n, seq_len)).astype(np.int64)
        # label correlated with token sum so models can learn
        self.y = ((self.x.sum(-1) // seq_len) % n_classes).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class Imdb(_SyntheticTextDataset):
    """~ text/datasets/imdb.py; reads local copy if present else synthetic."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/imdb.npz")
        if os.path.exists(local):
            d = np.load(local)
            self.x = d[f"x_{mode}"]
            self.y = d[f"y_{mode}"]
        else:
            super().__init__(5000 if mode == "train" else 1000, 128, 5000, 2,
                             seed=0 if mode == "train" else 1)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/housing.data")
        if os.path.exists(local):
            raw = np.loadtxt(local).astype(np.float32)
        else:
            rng = np.random.default_rng(0)
            feats = rng.standard_normal((506, 13)).astype(np.float32)
            w = rng.standard_normal(13).astype(np.float32)
            target = feats @ w + 0.1 * rng.standard_normal(506).astype(
                np.float32)
            raw = np.concatenate([feats, target[:, None]], 1)
        split = int(0.8 * len(raw))
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, i):
        return self.data[i, :-1], self.data[i, -1:]

    def __len__(self):
        return len(self.data)


class Conll05st(_SyntheticTextDataset):
    def __init__(self, data_file=None, mode="train", **kw):
        super().__init__(2000, 64, 8000, 20, seed=2)


class Movielens(_SyntheticTextDataset):
    def __init__(self, data_file=None, mode="train", **kw):
        super().__init__(4000, 16, 4000, 5, seed=3)


class ViterbiDecoder:
    """~ paddle.text.ViterbiDecoder (python/paddle/text/viterbi_decode.py):
    layer-style wrapper over :func:`viterbi_decode`."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class Imikolov(_SyntheticTextDataset):
    """~ text/datasets/imikolov.py (PTB-style n-gram LM dataset)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/imikolov.npz")
        self.window_size = window_size
        if os.path.exists(local):
            d = np.load(local)
            self.x = d[f"x_{mode}"]
            self.y = d[f"y_{mode}"]
        else:
            rng = np.random.default_rng(4 if mode == "train" else 5)
            grams = rng.integers(
                1, 2000, (8000 if mode == "train" else 1000, window_size))
            self.x = grams[:, :-1].astype(np.int64)
            self.y = grams[:, -1:].astype(np.int64)

    def __getitem__(self, i):
        return tuple(self.x[i]) + (self.y[i],)


class _SyntheticTranslationDataset(Dataset):
    """src/trg token-id pairs for WMT-style translation sets."""

    def __init__(self, n, src_len, trg_len, vocab, seed):
        rng = np.random.default_rng(seed)
        self.src = rng.integers(2, vocab, (n, src_len)).astype(np.int64)
        self.trg = rng.integers(2, vocab, (n, trg_len)).astype(np.int64)

    def __getitem__(self, i):
        src = self.src[i]
        trg = self.trg[i]
        # (src_ids, trg_ids, trg_ids_next) like the reference
        return src, trg[:-1], trg[1:]

    def __len__(self):
        return len(self.src)


class WMT14(_SyntheticTranslationDataset):
    """~ text/datasets/wmt14.py; local file or deterministic synthetic."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        super().__init__(4000 if mode == "train" else 500, 20, 21,
                         min(dict_size, 30000), seed=6)
        self.dict_size = dict_size


class WMT16(_SyntheticTranslationDataset):
    """~ text/datasets/wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(4000 if mode == "train" else 500, 24, 25,
                         min(src_dict_size, 30000), seed=7)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size

from . import strings  # noqa: F401,E402
from .strings import (FasterTokenizer, StringTensor,  # noqa: F401,E402
                      to_string_tensor)
