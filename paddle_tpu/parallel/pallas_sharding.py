"""Manual sharding wrapper for Pallas attention kernels.

GSPMD cannot partition a Pallas custom call over ANY dimension: left to
itself it all-gathers the operands around the kernel (measured on a
2-layer TP=2 x dp=2 Llama step: 36 all-gathers / 27.3 MB per step vs 0 on
the dense path). Every flash-attention call site therefore routes through
``shard_map_attention``: heads go manual over the 'model' axis and batch
over 'data' when divisible, other mesh axes stay with GSPMD.

One implementation for the three call-site families (LlamaAttention,
llama_functional.layer_forward inside the partial-manual pipeline, and
the public nn.functional.scaled_dot_product_attention) so guards cannot
drift.
"""
from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ..jax_compat import get_context_mesh
from ..jax_compat import shard_map as _shard_map

# test hook: set True whenever a wrapped (manual) kernel launch is traced
ENGAGED = {"flag": False}


def shard_map_attention(fn, q, k, v, mesh=None, head_axis: str = "model",
                        batch_axis: str = "data"):
    """Run ``fn(q, k, v)`` (layout (B, H, S, D); k/v may carry fewer heads
    — GQA) with the head dim manual over ``head_axis`` and the batch dim
    manual over ``batch_axis`` when divisible.

    mesh=None probes the context abstract mesh (pjit/GSPMD traces and
    nested shard_map regions — only AUTO axes are eligible there); a
    concrete mesh skips the probe (the train-step factories pass theirs).
    Falls back to a plain ``fn(q, k, v)`` call whenever manual sharding
    does not apply.
    """
    if mesh is None:
        amesh, eligible = get_context_mesh()
        if head_axis not in eligible:
            return fn(q, k, v)
        mesh = amesh
    else:
        eligible = mesh.axis_names
    if (head_axis not in mesh.axis_names
            or mesh.shape[head_axis] <= 1
            or q.shape[1] % mesh.shape[head_axis]
            or k.shape[1] % mesh.shape[head_axis]):
        return fn(q, k, v)
    b_ax = batch_axis if (batch_axis in eligible
                          and mesh.shape.get(batch_axis, 1) > 1
                          and q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(b_ax, head_axis, None, None)
    manual = frozenset({head_axis} | ({b_ax} if b_ax else set()))
    out = _shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                     out_specs=spec, check_vma=False,
                     axis_names=manual)(q, k, v)
    ENGAGED["flag"] = True  # after the call: a tracing failure above must
    #                         not leave the marker set (call sites may
    #                         catch and fall back)
    return out
