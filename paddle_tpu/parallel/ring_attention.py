"""Ring attention over a 'sep' mesh axis (context parallelism).

The reference has NO sequence/context parallelism (SURVEY.md §0/§5) —
this is an exceeds-reference capability. Sequence is sharded over the
ring axis; each device computes blockwise attention of its local Q
against the currently-held K/V chunk, then passes the chunk to its
neighbor over ICI via ppermute. Compute (local attention block)
overlaps the rotation; after n steps every Q chunk has seen every K/V
chunk.

Causal masking uses global block positions: chunk c attends chunk k
fully when k < c, causally (triangular) when k == c, not at all when
k > c.

Two local-attention engines:

- **flash kernel path** (default for MXU-shaped chunks): each chunk
  pair runs the Pallas flash kernel's forward, producing normalized
  partial (out, lse); partials merge online in log space. The custom
  VJP re-runs the ring in the backward, calling the flash backward
  kernel per chunk with the GLOBAL (out, lse, dO) — mathematically the
  chunk-restricted softmax gradient, the classical ring-attention
  backward. dK/dV accumulators rotate with their chunks and arrive
  home after the full cycle. No (Sq, Sk) score tensor ever
  materializes, so memory is O(block) regardless of S — the dense
  einsum engine below OOMed at S=16384 (12.9 GB of f32 scores) and
  measured 0.29-0.46x flash throughput at S=2k-8k
  (tools/seq_attn_bench.py, 2026-08-01).
- **dense einsum fallback** for flash-ineligible shapes (tiny heads,
  odd lengths, CPU oracle tests): exact f32 softmax over the chunk.

GQA: K/V rotate at their TRUE head count (G-times less ICI traffic);
the flash path repeats them to full heads locally after each hop.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..jax_compat import shard_map as _shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """Dense-engine partials: q (B,Hq,Sq,D) pre-scaled f32; k/v
    (B,Hkv,Sk,D) with Hq a multiple of Hkv; mask broadcastable (Sq,Sk)
    bool. Returns (scores_max, exp_sum, acc) partials in f32 with Hq
    heads."""
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv  # G == 1 is plain MHA (the reshape below is free)
    qg = q.reshape(B, Hkv, G, Sq, D).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k.astype(jnp.float32))
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, -1)
    # guard fully-masked rows
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, -1)
    acc = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return (m_safe.reshape(B, Hq, Sq), l.reshape(B, Hq, Sq),
            acc.reshape(B, Hq, Sq, D))


def _ring_flash_local(axis: str, n: int, causal: bool, sm_scale: float):
    """Builds the per-device (custom-VJP) ring function for the flash
    engine. ql: (B,Hq,Sloc,D); kl/vl: (B,Hkv,Sloc,D)."""
    from ..ops.pallas.flash_attention import _fa_bwd, _fa_fwd

    def _expand(kb, vb, G):
        if G == 1:
            return kb, vb
        return jnp.repeat(kb, G, axis=1), jnp.repeat(vb, G, axis=1)

    def _chunk_fwd(ql, kb, vb, diag_causal: bool):
        out, res = _fa_fwd(ql, kb, vb, diag_causal, sm_scale,
                           None, None, None, None, None)
        return out, res[4]  # (out, lse)

    def _merge(O, LSE, o, lse):
        LSE_new = jnp.logaddexp(LSE, lse)
        wO = jnp.exp(LSE - LSE_new)[..., None]
        wo = jnp.exp(lse - LSE_new)[..., None]
        return O * wO + o.astype(jnp.float32) * wo, LSE_new

    def fwd_loop(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        B, Hq, Sq, D = ql.shape
        G = Hq // kl.shape[1]
        O = jnp.zeros((B, Hq, Sq, D), jnp.float32)
        LSE = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)

        def step(carry, i):
            O, LSE, kb, vb = carry
            src = (my - i) % n
            kf, vf = _expand(kb, vb, G)

            def diag_fn(ops):
                return _chunk_fwd(*ops, diag_causal=True)

            def full_fn(ops):
                return _chunk_fwd(*ops, diag_causal=False)

            def none_fn(ops):
                return (jnp.zeros((B, Hq, Sq, D), ql.dtype),
                        jnp.full((B, Hq, Sq), NEG_INF, jnp.float32))

            ops = (ql, kf, vf)
            if causal:
                o, lse = jax.lax.cond(
                    src == my, diag_fn,
                    lambda ops: jax.lax.cond(src < my, full_fn, none_fn,
                                             ops), ops)
            else:
                o, lse = full_fn(ops)
            O, LSE = _merge(O, LSE, o, lse)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return (O, LSE, kb, vb), None

        (O, LSE, _, _), _ = jax.lax.scan(
            step, (O, LSE, kl, vl), jnp.arange(n))
        return O.astype(ql.dtype), LSE

    @jax.custom_vjp
    def ring(ql, kl, vl):
        return fwd_loop(ql, kl, vl)[0]

    def ring_fwd(ql, kl, vl):
        O, LSE = fwd_loop(ql, kl, vl)
        return O, (ql, kl, vl, O, LSE)

    def ring_bwd(res, dO):
        ql, kl, vl, O, LSE = res
        my = jax.lax.axis_index(axis)
        B, Hq, Sq, D = ql.shape
        Hkv = kl.shape[1]
        G = Hq // Hkv
        dq = jnp.zeros(ql.shape, jnp.float32)
        dk_acc = jnp.zeros(kl.shape, jnp.float32)
        dv_acc = jnp.zeros(vl.shape, jnp.float32)
        # delta = sum(dO*O) depends only on the (global) output — hoist
        # the reduction out of the ring scan instead of recomputing it
        # once per ring step inside _fa_bwd
        delta = jnp.sum(dO.astype(jnp.float32) * O.astype(jnp.float32),
                        axis=-1)

        def chunk_bwd(diag_causal, ops):
            ql, kf, vf = ops
            # flash backward with the GLOBAL (out, lse): p = exp(s - LSE)
            # is the global softmax restricted to this chunk, so the
            # returned (dq, dk, dv) are exactly this chunk's terms
            dql, dkf, dvf = _fa_bwd(diag_causal, sm_scale, None, None,
                                    None, None, None,
                                    (ql, kf, vf, O, LSE), dO, delta=delta)
            if G > 1:
                dkf = dkf.reshape(B, Hkv, G, dkf.shape[2], D).sum(2)
                dvf = dvf.reshape(B, Hkv, G, dvf.shape[2], D).sum(2)
            return (dql.astype(jnp.float32), dkf.astype(jnp.float32),
                    dvf.astype(jnp.float32))

        def step(carry, i):
            dq, dk_acc, dv_acc, kb, vb = carry
            src = (my - i) % n
            kf, vf = _expand(kb, vb, G)
            zero = (jnp.zeros(ql.shape, jnp.float32),
                    jnp.zeros(kb.shape, jnp.float32),
                    jnp.zeros(vb.shape, jnp.float32))
            ops = (ql, kf, vf)
            if causal:
                dql, dkb, dvb = jax.lax.cond(
                    src == my,
                    lambda ops: chunk_bwd(True, ops),
                    lambda ops: jax.lax.cond(
                        src < my, lambda ops: chunk_bwd(False, ops),
                        lambda ops: zero, ops), ops)
            else:
                dql, dkb, dvb = chunk_bwd(False, ops)
            dq = dq + dql
            dk_acc = dk_acc + dkb
            dv_acc = dv_acc + dvb
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            # accumulators ride with their chunks: after the full cycle
            # each chunk's dK/dV arrives back at its home device
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
            return (dq, dk_acc, dv_acc, kb, vb), None

        (dq, dk_acc, dv_acc, _, _), _ = jax.lax.scan(
            step, (dq, dk_acc, dv_acc, kl, vl), jnp.arange(n))
        return (dq.astype(ql.dtype), dk_acc.astype(kl.dtype),
                dv_acc.astype(vl.dtype))

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def ring_window_active_steps(n: int, window: int, Sloc: int) -> int:
    """Ring steps that can carry any live (query, key) pair under a
    sliding window: the pair at chunk distance d has minimum
    q_pos - k_pos = (d-1)*Sloc + 1, live iff < window. Steps beyond
    that are wholly outside the band and are SKIPPED — the window-aware
    ring's whole point (round-4 verdict item 5)."""
    if window <= 1:
        # only the diagonal can be live: the nearest cross-position
        # pair has gap 1, dead for window <= 1 — the generic formula
        # overshot by one here, costing a fully-masked kernel call +
        # ppermute per layer (round-5 advice #1)
        return 1
    d_max = max(0, (window - 2)) // Sloc + 1
    return min(n, d_max + 1)


def _ring_window_splash_local(axis: str, n: int, window: int,
                              sm_scale: float, Sloc: int):
    """Kernel-grade window x sep: per chunk pair (distance d) the banded
    splash kernel computes (out, lse) partials in the SHIFTED query
    frame (q_offset = d*Sloc), merged online in log space exactly like
    the flash ring. Only `n_active` ring steps run; later chunk pairs
    are wholly outside the band."""
    import numpy as np

    from ..ops.pallas.splash_attention import (_splash_bwd, _splash_fwd,
                                               banded_block_mask,
                                               pick_splash_blocks)

    n_act = ring_window_active_steps(n, window, Sloc)

    def _pair_mask(d, bq, bk):
        if d == 0:
            return banded_block_mask(Sloc, Sloc, bq, bk, window)
        nq, nk = Sloc // bq, Sloc // bk
        bm = np.zeros((nq, nk), bool)
        for i in range(nq):
            for j in range(nk):
                # min q_pos - k_pos within the block pair at distance d
                min_gap = d * Sloc + i * bq - (j + 1) * bk + 1
                bm[i, j] = min_gap < window
        return bm

    def _merge(O, LSE, o, lse):
        LSE_new = jnp.logaddexp(LSE, lse)
        wO = jnp.exp(LSE - LSE_new)[..., None]
        wo = jnp.exp(lse - LSE_new)[..., None]
        return O * wO + o.astype(jnp.float32) * wo, LSE_new

    def _blocks(G):
        return pick_splash_blocks(Sloc, Sloc, G)

    def fwd_loop(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        B, Hq, Sq, D = ql.shape
        G = Hq // kl.shape[1]
        bq, bk = _blocks(G)
        O = jnp.zeros((B, Hq, Sq, D), jnp.float32)
        LSE = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)
        kb, vb = kl, vl
        for d in range(n_act):
            bm = _pair_mask(d, bq, bk)
            o, res = _splash_fwd(ql, kb, vb, bm, d == 0, sm_scale,
                                 bq, bk, window, d * Sloc)
            lse = res[4]
            valid = my >= d  # wrapped chunks are acausal: contribute 0
            lse = jnp.where(valid, lse, NEG_INF)
            o = jnp.where(valid, o, 0).astype(o.dtype)
            O, LSE = _merge(O, LSE, o, lse)
            if d + 1 < n_act:
                perm = [(j, (j + 1) % n) for j in range(n)]
                kb = jax.lax.ppermute(kb, axis, perm)
                vb = jax.lax.ppermute(vb, axis, perm)
        return O.astype(ql.dtype), LSE

    @jax.custom_vjp
    def ring(ql, kl, vl):
        return fwd_loop(ql, kl, vl)[0]

    def ring_fwd(ql, kl, vl):
        O, LSE = fwd_loop(ql, kl, vl)
        return O, (ql, kl, vl, O, LSE)

    def ring_bwd(res, dO):
        ql, kl, vl, O, LSE = res
        my = jax.lax.axis_index(axis)
        B, Hq, Sq, D = ql.shape
        G = Hq // kl.shape[1]
        bq, bk = _blocks(G)
        dq = jnp.zeros(ql.shape, jnp.float32)
        dk_acc = jnp.zeros(kl.shape, jnp.float32)
        dv_acc = jnp.zeros(vl.shape, jnp.float32)
        kb, vb = kl, vl
        # delta = sum(dO*O) depends only on the GLOBAL (out, dO) —
        # identical every ring step, so reduce once here instead of
        # inside each _splash_bwd call (mirrors the flash ring's
        # _fa_bwd delta hoist; round-5 advice #2)
        delta = jnp.sum(dO.astype(jnp.float32) * O.astype(jnp.float32),
                        axis=-1)
        for d in range(n_act):
            bm = _pair_mask(d, bq, bk)
            # splash backward with the GLOBAL (out, lse): the softmax
            # gradient decomposes per key chunk (same argument as the
            # flash ring) and dK/dV come back at the true kv-head count
            dql, dkb, dvb = _splash_bwd(bm, d == 0, sm_scale, bq, bk,
                                        window, d * Sloc,
                                        (ql, kb, vb, O, LSE), dO,
                                        delta=delta)
            valid = (my >= d).astype(jnp.float32)
            dq = dq + dql.astype(jnp.float32) * valid
            dk_acc = dk_acc + dkb.astype(jnp.float32) * valid
            dv_acc = dv_acc + dvb.astype(jnp.float32) * valid
            perm = [(j, (j + 1) % n) for j in range(n)]
            # accumulators ride with their chunks
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
            if d + 1 < n_act:
                kb = jax.lax.ppermute(kb, axis, perm)
                vb = jax.lax.ppermute(vb, axis, perm)
        # chunks rotated n_act hops from home: deliver dK/dV back in one
        # permute instead of finishing the full cycle (the skipped steps
        # carry no gradient)
        if n_act < n:
            perm_home = [(j, (j - n_act) % n) for j in range(n)]
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm_home)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm_home)
        return (dq.astype(ql.dtype), dk_acc.astype(kl.dtype),
                dv_acc.astype(vl.dtype))

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def _dense_window_ring(axis: str, n: int, window: int, sm_scale: float,
                       Sloc: int, causal: bool = True):
    """Dense (exact f32, autodiff-able) window x sep engine: the CPU
    oracle for the splash ring and the fallback for splash-ineligible
    chunk shapes. Static per-distance masks; same early termination."""
    n_act = ring_window_active_steps(n, window, Sloc)

    def spmd(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        ql32 = ql.astype(jnp.float32) * sm_scale
        Sq = ql.shape[2]
        m = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
        l = jnp.zeros(ql.shape[:3], jnp.float32)
        acc = jnp.zeros(ql32.shape, jnp.float32)
        kb, vb = kl, vl
        for d in range(n_act):
            qp = d * Sloc + jnp.arange(Sq)[:, None]
            kp = jnp.arange(kb.shape[2])[None, :]
            mask = (qp - kp) < window
            if causal:
                mask &= qp >= kp
            bm_, bl, bacc = _block_attn(ql32, kb, vb, mask)
            valid = my >= d
            bm_ = jnp.where(valid, bm_, NEG_INF)
            bl = jnp.where(valid, bl, 0.0)
            bacc = jnp.where(valid, bacc, 0.0)
            m_new = jnp.maximum(m, bm_)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(bm_ - m_new)
            l = alpha * l + beta * bl
            acc = acc * alpha[..., None] + bacc * beta[..., None]
            m = m_new
            if d + 1 < n_act:
                perm = [(j, (j + 1) % n) for j in range(n)]
                kb = jax.lax.ppermute(kb, axis, perm)
                vb = jax.lax.ppermute(vb, axis, perm)
        l = jnp.where(l == 0.0, 1.0, l)
        return (acc / l[..., None]).astype(ql.dtype)

    return spmd


def ring_window_attention(q, k, v, mesh: Mesh, window: int,
                          axis: str = "sep", sm_scale=None,
                          batch_axis=None, head_axis=None):
    """Sliding-window attention composed with context parallelism: the
    seq dim shards over `axis` and the ring walks ONLY the chunk pairs
    the band touches (n_active of n steps — window 2048 at S=8192 over
    sep=4 runs 2 of 4). Replaces the round-4 ValueError at
    models/nlp/llama.py (window x 'sep' could not compose). q/k/v:
    GLOBAL (batch, heads, seq, head_dim); causal Mistral semantics
    (q_pos - k_pos < window)."""
    from ..ops.pallas.flash_attention import flash_eligible

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    h_ax = head_axis if head_axis in mesh.axis_names else None
    Sloc = q.shape[2] // max(1, n)
    use_splash = (q.shape[2] % max(1, n) == 0 and Sloc % 128 == 0
                  and flash_eligible(Sloc, q.shape[-1], q.dtype))
    if use_splash:
        spmd = _ring_window_splash_local(axis, n, window, sm_scale, Sloc)
    else:
        spmd = _dense_window_ring(axis, n, window, sm_scale, Sloc)
    spec = P(b_ax, h_ax, axis, None)
    fn = _shard_map(
        spmd, mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sep",
                   causal: bool = True, sm_scale=None,
                   batch_axis=None, head_axis=None):
    """q/k/v: GLOBAL (batch, heads, seq, head_dim) arrays (or sharded);
    seq dim is sharded over `axis` inside. batch_axis/head_axis optionally
    name mesh axes the batch/head dims are sharded over (composing context
    parallelism with data and tensor parallelism in one shard_map).
    Returns same-shape output."""
    from ..ops.pallas.flash_attention import flash_eligible

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    h_ax = head_axis if head_axis in mesh.axis_names else None
    Sloc = q.shape[2] // max(1, n)
    use_flash = (q.shape[2] % max(1, n) == 0
                 and flash_eligible(Sloc, q.shape[-1], q.dtype))

    if use_flash:
        spmd = _ring_flash_local(axis, n, causal, sm_scale)
    else:
        def spmd(ql, kl, vl):
            # dense fallback engine (exact f32 oracle; O(Sq*Sk) scores)
            my = jax.lax.axis_index(axis)
            ql32 = ql.astype(jnp.float32) * sm_scale
            Sq = ql.shape[2]

            m = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
            l = jnp.zeros(ql.shape[:3], jnp.float32)
            acc = jnp.zeros(ql32.shape, jnp.float32)

            def step(carry, i):
                m, l, acc, kb, vb = carry
                src_chunk = (my - i) % n  # whose KV we hold at step i
                if causal:
                    full = src_chunk < my
                    diag = src_chunk == my
                    tri = jnp.tril(jnp.ones((Sq, kb.shape[2]), bool))
                    mask = jnp.where(diag, tri, full)
                else:
                    mask = jnp.ones((Sq, kb.shape[2]), bool)
                bm, bl, bacc = _block_attn(ql32, kb, vb, mask)
                m_new = jnp.maximum(m, bm)
                alpha = jnp.exp(m - m_new)
                beta = jnp.exp(bm - m_new)
                l_new = alpha * l + beta * bl
                acc_new = acc * alpha[..., None] + bacc * beta[..., None]
                perm = [(j, (j + 1) % n) for j in range(n)]
                kb = jax.lax.ppermute(kb, axis, perm)
                vb = jax.lax.ppermute(vb, axis, perm)
                return (m_new, l_new, acc_new, kb, vb), None

            (m, l, acc, _, _), _ = jax.lax.scan(
                step, (m, l, acc, kl, vl), jnp.arange(n))
            l = jnp.where(l == 0.0, 1.0, l)
            return (acc / l[..., None]).astype(q.dtype)

    spec = P(b_ax, h_ax, axis, None)
    fn = _shard_map(
        spmd, mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec, check_vma=False)
    return fn(q, k, v)
