"""Ring attention — sequence/context parallelism over the 'sep' mesh axis.

The reference has NO sequence/context parallelism (grep-verified,
SURVEY.md §0/§5); this is the capability the TPU build adds to reach
long-context scale. Design: sequence sharded over 'sep'; each step every
device computes blockwise attention of its local Q against the currently
held KV chunk with online-softmax accumulation, then rotates KV one
neighbor over ICI via ppermute. Compute (local attention block) overlaps
the KV transfer thanks to XLA's latency-hiding scheduler — the classic
ring schedule.

Causal masking uses global block positions: chunk c attends chunk k fully
if k < c, diagonally if k == c, not at all if k > c (those steps still run
for SPMD uniformity; their contribution is masked to -inf).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """q: (B,Hq,Sq,D); k/v: (B,Hkv,Sk,D) with Hq a multiple of Hkv (GQA:
    the ring rotates K/V at their TRUE head count, so grouped-query
    configs move G-times less data over ICI per step); mask broadcastable
    (Sq,Sk) bool. Returns (scores_max, exp_sum, acc) partials in f32,
    shaped with Hq heads."""
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv  # G == 1 is plain MHA (the reshape below is free)
    qg = q.reshape(B, Hkv, G, Sq, D).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k.astype(jnp.float32))
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, -1)
    # guard fully-masked rows
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, -1)
    acc = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return (m_safe.reshape(B, Hq, Sq), l.reshape(B, Hq, Sq),
            acc.reshape(B, Hq, Sq, D))


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sep",
                   causal: bool = True, sm_scale=None,
                   batch_axis=None, head_axis=None):
    """q/k/v: GLOBAL (batch, heads, seq, head_dim) arrays (or sharded);
    seq dim is sharded over `axis` inside. batch_axis/head_axis optionally
    name mesh axes the batch/head dims are sharded over (composing context
    parallelism with data and tensor parallelism in one shard_map).
    Returns same-shape output."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    h_ax = head_axis if head_axis in mesh.axis_names else None

    def spmd(ql, kl, vl):
        # local chunks: (B,H,S/n,D)
        my = jax.lax.axis_index(axis)
        ql32 = ql.astype(jnp.float32) * sm_scale
        Sq = ql.shape[2]

        m = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
        l = jnp.zeros(ql.shape[:3], jnp.float32)
        acc = jnp.zeros(ql32.shape, jnp.float32)

        def step(carry, i):
            m, l, acc, kb, vb = carry
            src_chunk = (my - i) % n  # whose KV we hold at step i
            if causal:
                full = src_chunk < my
                diag = src_chunk == my
                tri = jnp.tril(jnp.ones((Sq, kb.shape[2]), bool))
                mask = jnp.where(diag, tri, full)
            else:
                mask = jnp.ones((Sq, kb.shape[2]), bool)
            bm, bl, bacc = _block_attn(ql32, kb, vb, mask)
            m_new = jnp.maximum(m, bm)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(bm - m_new)
            l_new = alpha * l + beta * bl
            acc_new = acc * alpha[..., None] + bacc * beta[..., None]
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return (m_new, l_new, acc_new, kb, vb), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (m, l, acc, kl, vl), jnp.arange(n))
        l = jnp.where(l == 0.0, 1.0, l)
        return (acc / l[..., None]).astype(q.dtype)

    spec = P(b_ax, h_ax, axis, None)
    fn = jax.shard_map(
        spmd, mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec, check_vma=False)
    return fn(q, k, v)
