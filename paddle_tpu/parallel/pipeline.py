"""Compiled pipeline parallelism.

Replaces the reference's pipeline machinery (SURVEY.md §2.2): the dygraph
1F1B loop (fleet/meta_parallel/pipeline_parallel.py:81), NCCL p2p protocol
(pp_utils/p2p_communication.py:217), static SectionWorker
(framework/section_worker.cc) and the fleet_executor actor runtime
(distributed/fleet_executor/carrier.h:49).

TPU-native form: ONE SPMD program. Stage parameters are stacked along a
leading axis sharded over the 'pipe' mesh axis; a lax.scan steps the
software pipeline; jax.lax.ppermute rotates activations stage->stage over
ICI. Backward is jax.grad of the scan (ppermute transposes to the reverse
rotation), with jax.checkpoint on the stage body bounding activation
memory — the compiled equivalent of 1F1B's schedule-managed buffers.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params):
    """[stage_tree_0, ...] -> one tree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   n_microbatches: int, axis: str = "pipe",
                   remat: bool = True, data_axis: str | None = None,
                   auto_axes=None, shard_input: bool = False):
    """Run ``stage_fn`` as a pipeline over mesh axis ``axis``.

    stage_fn(stage_params, activation) -> activation (same shape) — the body
    of ONE stage (e.g. a block of decoder layers).
    stacked_params: pytree, each leaf (n_stages, ...), sharded over `axis`.
    x: (batch, ...) global input; it is split into n_microbatches along
    batch inside the program.
    Returns y: (batch, ...) output of the last stage, replicated.

    Schedule: classic GPipe fill/steady/drain (n_micro + n_stages - 1
    ticks). Stage s at tick t computes micro (t - s). 1F1B's memory profile
    comes from remat + scan rather than schedule interleaving; the compiled
    program overlaps ppermute with the next tick's compute via XLA's
    latency-hiding scheduler.

    shard_input=True (requires n_microbatches % n_stages == 0): the
    microbatch buffer is sharded over the pipe axis instead of replicated
    — each stage stores M/P micros and the tick's micro is routed to
    stage 0 by a masked psum (one mb of comm per tick). Cuts the input
    buffer's per-stage memory by P at the cost of ~2x the final
    broadcast's comm volume spread over ticks.
    """
    n_stages = mesh.shape[axis]
    if shard_input and n_microbatches % n_stages != 0:
        raise ValueError(
            f"shard_input needs n_microbatches ({n_microbatches}) "
            f"divisible by n_stages ({n_stages})")
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def spmd(params, xm):
        # params: (1, ...) local stage slice; xm: microbatches — either
        # (M, mb, ...) replicated or (M/P, mb, ...) pipe-sharded
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        M = n_microbatches
        local_m = xm.shape[0]
        ticks = M + n_stages - 1
        state = jnp.zeros_like(xm[0])          # current activation buffer
        out_shape = (M,) + xm.shape[1:]
        outputs = jnp.zeros(out_shape, xm.dtype)  # last stage writes here

        def fetch_micro(xm, t):
            if not shard_input:
                mb_idx = jnp.clip(t, 0, M - 1)
                return jax.lax.dynamic_index_in_dim(xm, mb_idx, 0,
                                                    keepdims=False)
            # owner stage holds micro t at local index t % (M/P); route it
            # to everyone with a masked psum (stage 0 consumes)
            owner = jnp.clip(t, 0, M - 1) // local_m
            local_idx = jnp.clip(t, 0, M - 1) % local_m
            mine = jax.lax.dynamic_index_in_dim(xm, local_idx, 0,
                                                keepdims=False)
            return jax.lax.psum(
                jnp.where(stage == owner, 1.0, 0.0).astype(mine.dtype)
                * mine, axis)

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if in range) else keeps buffer
            injected = jax.lax.select(
                jnp.logical_and(stage == 0, t < M),
                fetch_micro(xm, t),
                state)
            out = body(params, injected)
            # last stage records micro (t - (n_stages-1))
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            write = jnp.logical_and(stage == n_stages - 1,
                                    t >= n_stages - 1)
            outputs = jax.lax.cond(
                write,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, out_idx, 0),
                lambda o: o, outputs)
            # rotate activations forward one stage over ICI
            nxt = jax.lax.ppermute(
                out, axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (state, outputs),
                                       jnp.arange(ticks))
        # everyone returns the last stage's outputs (broadcast over axis)
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, 1.0, 0.0) * outputs, axis)
        return outputs

    B = x.shape[0]
    mb = B // n_microbatches
    xm = x.reshape((n_microbatches, mb) + x.shape[1:])

    # batch (microbatch dim 1) may additionally shard over a data axis —
    # each data shard runs its own pipeline instance over the same stages
    in_axis0 = axis if shard_input else None
    x_spec = P(in_axis0, data_axis)
    out_spec = P(None, data_axis) if data_axis else P()
    in_specs = (jax.tree.map(lambda _: P(axis), stacked_params), x_spec)
    kw = {}
    if auto_axes:
        # partial-manual shard_map: 'pipe'/'data' rotate explicitly, the
        # listed axes (e.g. 'model' for TP, 'sharding' for ZeRO) stay with
        # GSPMD — the compiler partitions the stage body's matmuls from the
        # incoming param shardings (4D composition in ONE program)
        kw["axis_names"] = frozenset(
            a for a in mesh.axis_names if a not in auto_axes)
    fn = jax.shard_map(spmd, mesh=mesh, in_specs=in_specs,
                       out_specs=out_spec, check_vma=False, **kw)
    y = fn(stacked_params, xm)
    return y.reshape((B,) + y.shape[2:])
