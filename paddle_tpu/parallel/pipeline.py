"""Compiled pipeline parallelism.

Replaces the reference's pipeline machinery (SURVEY.md §2.2): the dygraph
1F1B loop (fleet/meta_parallel/pipeline_parallel.py:81), NCCL p2p protocol
(pp_utils/p2p_communication.py:217), static SectionWorker
(framework/section_worker.cc) and the fleet_executor actor runtime
(distributed/fleet_executor/carrier.h:49).

TPU-native form: ONE SPMD program. Stage parameters are stacked along a
leading axis sharded over the 'pipe' mesh axis; a lax.scan steps the
software pipeline; jax.lax.ppermute rotates activations stage->stage over
ICI. Backward is jax.grad of the scan (ppermute transposes to the reverse
rotation), with jax.checkpoint on the stage body bounding activation
memory — the compiled equivalent of 1F1B's schedule-managed buffers.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map as _shard_map


def stack_stage_params(per_stage_params):
    """[stage_tree_0, ...] -> one tree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   n_microbatches: int, axis: str = "pipe",
                   remat: bool = True, data_axis: str | None = None,
                   auto_axes=None, shard_input: bool = False):
    """Run ``stage_fn`` as a pipeline over mesh axis ``axis``.

    stage_fn(stage_params, activation) -> activation (same shape) — the body
    of ONE stage (e.g. a block of decoder layers).
    stacked_params: pytree, each leaf (n_stages, ...), sharded over `axis`.
    x: (batch, ...) global input; it is split into n_microbatches along
    batch inside the program.
    Returns y: (batch, ...) output of the last stage, replicated.

    Schedule: classic GPipe fill/steady/drain (n_micro + n_stages - 1
    ticks). Stage s at tick t computes micro (t - s). 1F1B's memory profile
    comes from remat + scan rather than schedule interleaving; the compiled
    program overlaps ppermute with the next tick's compute via XLA's
    latency-hiding scheduler.

    shard_input=True (requires n_microbatches % n_stages == 0): the
    microbatch buffer is sharded over the pipe axis instead of replicated
    — each stage stores M/P micros and the tick's micro is routed to
    stage 0 by a masked psum (one mb of comm per tick). Cuts the input
    buffer's per-stage memory by P at the cost of ~2x the final
    broadcast's comm volume spread over ticks.
    """
    n_stages = mesh.shape[axis]
    if shard_input and n_microbatches % n_stages != 0:
        raise ValueError(
            f"shard_input needs n_microbatches ({n_microbatches}) "
            f"divisible by n_stages ({n_stages})")
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def spmd(params, xm):
        # params: (1, ...) local stage slice; xm: microbatches — either
        # (M, mb, ...) replicated or (M/P, mb, ...) pipe-sharded
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        M = n_microbatches
        local_m = xm.shape[0]
        ticks = M + n_stages - 1
        state = jnp.zeros_like(xm[0])          # current activation buffer
        out_shape = (M,) + xm.shape[1:]
        outputs = jnp.zeros(out_shape, xm.dtype)  # last stage writes here

        def fetch_micro(xm, t):
            if not shard_input:
                mb_idx = jnp.clip(t, 0, M - 1)
                return jax.lax.dynamic_index_in_dim(xm, mb_idx, 0,
                                                    keepdims=False)
            # owner stage holds micro t at local index t % (M/P); route it
            # to everyone with a masked psum (stage 0 consumes)
            owner = jnp.clip(t, 0, M - 1) // local_m
            local_idx = jnp.clip(t, 0, M - 1) % local_m
            mine = jax.lax.dynamic_index_in_dim(xm, local_idx, 0,
                                                keepdims=False)
            return jax.lax.psum(
                jnp.where(stage == owner, 1.0, 0.0).astype(mine.dtype)
                * mine, axis)

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if in range) else keeps buffer
            injected = jax.lax.select(
                jnp.logical_and(stage == 0, t < M),
                fetch_micro(xm, t),
                state)
            out = body(params, injected)
            # last stage records micro (t - (n_stages-1))
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            write = jnp.logical_and(stage == n_stages - 1,
                                    t >= n_stages - 1)
            outputs = jax.lax.cond(
                write,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, out_idx, 0),
                lambda o: o, outputs)
            # rotate activations forward one stage over ICI
            nxt = jax.lax.ppermute(
                out, axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (state, outputs),
                                       jnp.arange(ticks))
        # everyone returns the last stage's outputs (broadcast over axis)
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, 1.0, 0.0) * outputs, axis)
        return outputs

    B = x.shape[0]
    mb = B // n_microbatches
    xm = x.reshape((n_microbatches, mb) + x.shape[1:])
    return _launch(spmd, stacked_params, xm, mesh, axis, data_axis,
                   auto_axes, shard_input, B, stage_leading_spec=P(axis))


def pipeline_apply_interleaved(stage_fn: Callable, stacked_params, x,
                               mesh: Mesh, n_microbatches: int,
                               n_virtual: int, axis: str = "pipe",
                               remat: bool = True,
                               data_axis: str | None = None,
                               auto_axes=None,
                               params_layout: str = "stacked"):
    """Breadth-first interleaved pipeline (virtual pipeline stages).

    Exceeds both the GPipe schedule above and the reference's 1F1B (which
    carries a comment that interleaving is NOT implemented,
    pipeline_parallel.py:84): global stage s = v*P + d lives on device
    s % P as virtual chunk v = s // P (Megatron-style round-robin
    placement), and micro m's stage s runs at tick

        t(m, s) = (m // P)*P*V + s + (m % P)

    which satisfies the hop dependency t(m, s) = t(m, s-1) + 1 under a
    uniform +1 ring rotation — INCLUDING the wrap from device P-1 back to
    device 0 (the activation re-enters one tick later as chunk v+1, so no
    inter-chunk buffering exists at all). Every device does exactly one
    stage-computation per tick for the whole M*V working window: the only
    bubble is the ring skew, (P-1)/(M*V + P - 1) — a factor V smaller
    than GPipe's (P-1)/(M + P - 1).

    stacked_params: pytree with leading axis Sg = P*V in global stage
    order (params_layout="stacked"), or already laid out as (V, P, ...)
    with axis 1 sharded over `axis` (params_layout="vp" — what a train
    step should keep between iterations to avoid relayout). Requires
    n_microbatches % P == 0.
    """
    n_stages = mesh.shape[axis]
    V = n_virtual
    if V < 1:
        raise ValueError("n_virtual must be >= 1")
    if n_microbatches % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_microbatches "
            f"({n_microbatches}) divisible by n_stages ({n_stages})")
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape
    if params_layout == "vp":
        if lead[0] != V or lead[1] != n_stages:
            raise ValueError(
                f"vp-layout params lead with {lead[:2]}, expected "
                f"({V}, {n_stages})")
        params_vp = stacked_params
    else:
        if lead[0] != n_stages * V:
            raise ValueError(
                f"stacked params carry {lead[0]} stages, expected "
                f"n_stages*n_virtual = {n_stages * V}")
        # (Sg, ...) -> (V, P, ...): element [v, d] is global stage v*P + d
        params_vp = jax.tree.map(
            lambda l: l.reshape((V, n_stages) + l.shape[1:]), stacked_params)
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def spmd(params, xm):
        # params leaf: (V, 1, ...) local slice -> (V, ...)
        params = jax.tree.map(lambda p: p[:, 0], params)
        d = jax.lax.axis_index(axis)
        P_ = n_stages
        M = n_microbatches
        PV = P_ * V
        work = M * V
        ticks = work + P_ - 1
        state = jnp.zeros_like(xm[0])
        outputs = jnp.zeros((M,) + xm.shape[1:], xm.dtype)

        def tick(carry, t):
            state, outputs = carry
            u = t - d
            valid = jnp.logical_and(u >= 0, u < work)
            uc = jnp.clip(u, 0, work - 1)
            g = uc // PV
            v = (uc % PV) // P_
            r = uc % P_
            m = g * P_ + r
            inject = jnp.logical_and(jnp.logical_and(d == 0, v == 0), valid)
            x_in = jax.lax.select(
                inject,
                jax.lax.dynamic_index_in_dim(xm, m, 0, keepdims=False),
                state)
            pv = jax.tree.map(
                lambda l: jax.lax.dynamic_index_in_dim(l, v, 0,
                                                       keepdims=False),
                params)
            out = body(pv, x_in)
            emit = jnp.logical_and(
                jnp.logical_and(d == P_ - 1, v == V - 1), valid)
            outputs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(o, out, m, 0),
                lambda o: o, outputs)
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % P_) for i in range(P_)])
            return (nxt, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (state, outputs),
                                       jnp.arange(ticks))
        outputs = jax.lax.psum(
            jnp.where(d == P_ - 1, 1.0, 0.0) * outputs, axis)
        return outputs

    B = x.shape[0]
    mb = B // n_microbatches
    xm = x.reshape((n_microbatches, mb) + x.shape[1:])
    return _launch(spmd, params_vp, xm, mesh, axis, data_axis, auto_axes,
                   False, B, stage_leading_spec=P(None, axis))


def _launch(spmd, params, xm, mesh, axis, data_axis, auto_axes,
            shard_input, B, stage_leading_spec):

    # batch (microbatch dim 1) may additionally shard over a data axis —
    # each data shard runs its own pipeline instance over the same stages
    in_axis0 = axis if shard_input else None
    x_spec = P(in_axis0, data_axis)
    out_spec = P(None, data_axis) if data_axis else P()
    in_specs = (jax.tree.map(lambda _: stage_leading_spec, params), x_spec)
    kw = {}
    if auto_axes:
        # partial-manual shard_map: 'pipe'/'data' rotate explicitly, the
        # listed axes (e.g. 'model' for TP, 'sharding' for ZeRO) stay with
        # GSPMD — the compiler partitions the stage body's matmuls from the
        # incoming param shardings (4D composition in ONE program)
        kw["axis_names"] = frozenset(
            a for a in mesh.axis_names if a not in auto_axes)
    fn = _shard_map(spmd, mesh=mesh, in_specs=in_specs,
                    out_specs=out_spec, check_vma=False, **kw)
    y = fn(params, xm)
    return y.reshape((B,) + y.shape[2:])
