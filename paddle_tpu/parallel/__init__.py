"""Compiled parallelism primitives (the perf path).

The eager wrappers in distributed/fleet provide API parity; this package is
where the TPU-native execution actually scales:
  pipeline.py        — GPipe/1F1B pipeline as shard_map + ppermute + scan
                       over the 'pipe' mesh axis (replaces SectionWorker /
                       p2p_communication / fleet_executor interceptors)
  ring_attention.py  — sequence/context parallelism over the 'sep' axis
                       (ppermute KV rotation; absent from the reference,
                       SURVEY.md §5)
"""
from .pipeline import pipeline_apply, stack_stage_params  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
