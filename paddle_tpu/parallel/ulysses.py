"""Ulysses (DeepSpeed-style) sequence parallelism: head-scatter all-to-all.

Sister strategy to ring attention (parallel/ring_attention.py) for the
'sep' axis — the reference has neither (SURVEY.md §0/§5). Where the ring
rotates KV chunks P times over ICI, Ulysses does TWO all-to-alls total:

    in : (B, H,   S/P, D) sequence-sharded
    a2a: (B, H/P, S,   D) head-sharded     <- full sequence per device
    ... exact local attention over the full sequence ...
    a2a: (B, H,   S/P, D) sequence-sharded again

Comm volume is O(2·B·S·H·D/P) regardless of sequence length, vs the
ring's P·(KV volume); Ulysses wins when H >= P and attention is dense;
the ring wins when H < P or memory forbids holding the full sequence.
Exposing both lets the topology/planner pick per config.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map as _shard_map


def _local_attention(q, k, v, causal: bool, sm_scale: float):
    """Attention on local (B, h, S, D) blocks. After the all-to-all each
    device holds the FULL sequence for its head shard, so this is plain
    attention — route through the flash kernel when shapes allow (chip:
    the dense-einsum path measured 0.47x flash throughput and O(S^2)
    memory, tools/seq_attn_bench.py), exact dense softmax otherwise."""
    from ..ops.pallas.flash_attention import flash_attention, flash_eligible
    if flash_eligible(q.shape[2], q.shape[-1], q.dtype):
        return flash_attention(q, k, v, causal, sm_scale)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sep",
                      causal: bool = True, sm_scale=None):
    """q/k/v: GLOBAL (batch, heads, seq, head_dim); the seq dim is sharded
    over mesh axis ``axis`` on entry and exit; internally heads are
    sharded instead (two lax.all_to_all hops). Heads must divide the axis
    size. Differentiable (shard_map of pure jnp ops)."""
    B, H, S, D = q.shape
    n = mesh.shape[axis]
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by '{axis}' size {n}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)

    def local(ql, kl, vl):
        # local blocks arrive (B, H, S/P, D); exchange seq-shards for
        # head-shards: concat seq along axis 2, split heads along axis 1
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh = seq_to_heads(ql)          # (B, H/P, S, D)
        kh = seq_to_heads(kl)
        vh = seq_to_heads(vl)
        oh = _local_attention(qh, kh, vh, causal, sm_scale)
        return heads_to_seq(oh)        # (B, H, S/P, D)

    spec = P(None, None, axis, None)
    fn = _shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)
    sh = NamedSharding(mesh, spec)
    with mesh:
        return fn(jax.device_put(q, sh), jax.device_put(k, sh),
                  jax.device_put(v, sh))
