"""paddle.device namespace equivalent (python/paddle/device/__init__.py)."""
from ..core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)


def cuda_device_count() -> int:  # API-compat shim: "cuda" means accelerator
    return device_count()


# ---- memory stats ----------------------------------------------------------
# ~ paddle/fluid/memory/stats.h:35 (peak/current allocated+reserved per
# device, exposed as paddle.device.cuda.max_memory_allocated etc.). Backed
# by the runtime's per-device memory_stats() (XLA allocator counters);
# jax owns the BFC-style caching allocator that AllocatorFacade provides in
# the reference.

def _dev(device=None):
    import jax
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, Place):
        return device.jax_device
    return device


def memory_stats(device=None) -> dict:
    d = _dev(device)
    stats = d.memory_stats() if hasattr(d, "memory_stats") else None
    return dict(stats) if stats else {}


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("pool_bytes", s.get(
        "bytes_limit", 0))))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved", memory_reserved(device)))


def reset_peak_memory_stats(device=None) -> None:
    # XLA exposes no peak reset; deleting dead buffers is the useful part
    empty_cache()


def reset_max_memory_allocated(device=None) -> None:
    reset_peak_memory_stats(device)


def empty_cache() -> None:
    """~ paddle.device.cuda.empty_cache: return cached blocks. Live arrays
    are owned by Python references here, so freeing = dropping dead
    client-side buffers."""
    import gc
    gc.collect()


class cuda:
    """paddle.device.cuda namespace shim (accelerator = TPU)."""
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)
    device_count = staticmethod(cuda_device_count)

    @staticmethod
    def synchronize(device=None):
        import jax
        # block on all outstanding work for the device
        jax.effects_barrier()


# ---- paddle.device namespace completion ------------------------------------
from ..core.place import (  # noqa: F401,E402
    CUDAPinnedPlace, CUDAPlace, NPUPlace, XPUPlace,
)


class IPUPlace(TPUPlace):
    """API-compat alias (Graphcore slot; accelerator here is the TPU)."""


class MLUPlace(TPUPlace):
    """API-compat alias."""


def get_all_device_type() -> list:
    import jax
    kinds = []
    for d in jax.devices():
        if d.platform not in kinds:
            kinds.append(d.platform)
    if "cpu" not in kinds:
        kinds.append("cpu")
    return kinds


def get_all_custom_device_type() -> list:
    from .custom_device import get_all_custom_device_type as _g
    return _g()


def get_available_device() -> list:
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device() -> list:
    from .custom_device import _REGISTERED, get_device_count
    out = []
    for name in _REGISTERED:
        out.extend(f"{name}:{i}" for i in range(get_device_count(name)))
    return out


def get_cudnn_version():
    """No cuDNN on TPU — None like the reference on non-CUDA builds."""
    return None


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    """XLA plays the compiler role natively; CINN flag reports False."""
    return False
