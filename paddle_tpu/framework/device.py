"""paddle.device namespace equivalent (python/paddle/device/__init__.py)."""
from ..core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)


def cuda_device_count() -> int:  # API-compat shim: "cuda" means accelerator
    return device_count()
