"""Serialization: paddle.save / paddle.load equivalent.

~ python/paddle/framework/io.py:572,788 — pickle nested state dicts with
tensors converted to numpy. Sharded/async distributed checkpointing lives in
paddle_tpu.distributed.checkpoint (orbax-backed); this is the single-host
object-pickle path.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Parameter, Tensor


def _to_serializable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient,
                "param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj: Any, return_numpy: bool = False) -> Any:
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("param") else Tensor
            if cls is Parameter:
                return Parameter(obj["data"],
                                 trainable=not obj["stop_gradient"])
            return Tensor(obj["data"], stop_gradient=obj["stop_gradient"])
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_serializable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4) -> None:
    """Atomic: pickle to a tmp file in the SAME directory, then
    ``os.replace`` onto the final path (the tmp->mv discipline
    incubate/checkpoint/auto_checkpoint.py follows). A crash or
    pickling error mid-write can therefore never leave a truncated
    file where a valid checkpoint used to be."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_to_serializable(obj), f, protocol=protocol)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load(path: str, return_numpy: bool = False) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy=return_numpy)
