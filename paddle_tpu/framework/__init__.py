"""Framework-level utilities: save/load, device namespace, random."""
from . import io  # noqa: F401
from . import device  # noqa: F401

from ..core.selected_rows import SelectedRows  # noqa: F401,E402
