"""Framework-level utilities: save/load, device namespace, random."""
from . import io  # noqa: F401
from . import device  # noqa: F401
