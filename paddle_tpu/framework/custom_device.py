"""Custom-device plugin slot.

~ paddle/phi/backends/device_ext.h ``C_DeviceInterface`` + custom_device.cc
:692 (dlopen + InitPlugin): the reference lets vendors ship a shared object
implementing a C device ABI, discovered from CUSTOM_DEVICE_ROOT.

TPU-native equivalent: the PJRT plugin ABI — jax discovers backend plugins
(shared objects exporting GetPjrtApi) via explicit registration or the
``jax_plugins`` entry-point namespace. This module is the paddle-flavored
registration surface over it, plus a fake test double (the
fake_cpu_device.h role) that aliases the CPU backend so plugin-path code is
testable without vendor hardware.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

_REGISTERED: Dict[str, dict] = {}
_FAKE_DEVICES: Dict[str, str] = {}


def register_custom_device(name: str, library_path: Optional[str] = None,
                           options: Optional[dict] = None) -> None:
    """Register a PJRT plugin as a named custom device.

    library_path: shared object exporting ``GetPjrtApi`` (the PJRT C ABI —
    the C_DeviceInterface analog). Must exist at call time.
    """
    if library_path is not None:
        if not os.path.exists(library_path):
            raise FileNotFoundError(
                f"custom device plugin not found: {library_path}")
        from jax._src import xla_bridge
        xla_bridge.register_plugin(name, library_path=library_path,
                                   options=options or {})
    _REGISTERED[name] = {"library_path": library_path,
                         "options": options or {}}


def register_fake_device(name: str, backend: str = "cpu") -> None:
    """Test double (~ phi/backends/custom/fake_cpu_device.h): alias an
    existing backend under a custom device name so plugin-path code can be
    exercised hardware-free."""
    _FAKE_DEVICES[name] = backend
    _REGISTERED[name] = {"library_path": None, "fake_backend": backend,
                         "options": {}}


def get_all_custom_device_type() -> list:
    """~ paddle.device.get_all_custom_device_type."""
    return sorted(_REGISTERED)


def is_custom_device(name: str) -> bool:
    return name in _REGISTERED


def get_device_count(name: str) -> int:
    import jax
    if name in _FAKE_DEVICES:
        return len(jax.devices(_FAKE_DEVICES[name]))
    try:
        return len(jax.devices(name))
    except RuntimeError:
        return 0


def devices(name: str) -> list:
    import jax
    if name in _FAKE_DEVICES:
        return jax.devices(_FAKE_DEVICES[name])
    return jax.devices(name)


def unregister_custom_device(name: str) -> None:
    _REGISTERED.pop(name, None)
    _FAKE_DEVICES.pop(name, None)
