"""SSD/RetinaNet-era detection ops.

~ python/paddle/fluid/layers/detection.py (prior_box:1778,
anchor_generator:2413, box_coder:819, iou_similarity:765, box_clip:3057,
multiclass_nms:3276) and their C++ ops under
paddle/fluid/operators/detection/. TPU-shaped where it matters:
prior/anchor generation and box coding are pure array math (jit-able,
static shapes); multiclass_nms returns FIXED-size keep_top_k-padded
results (label -1 padding) when keep_top_k >= 0 instead of the
reference's LoD variable-length outputs — the standard
accelerator-side detection post-processing contract (keep_top_k < 0
keeps everything and is host-only, data-dependent width).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from .ops import box_iou


def _arr(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


def _traced(*xs):
    """True when any input is a JAX tracer — the caller is inside jit,
    so the op must route to its detection_jit twin (numpy would fail on
    the tracer and host-sync the step)."""
    import jax.core
    for x in xs:
        v = x._value if isinstance(x, Tensor) else x
        if isinstance(v, jax.core.Tracer):
            return True
    return False


def _jval(x):
    return x._value if isinstance(x, Tensor) else x


def iou_similarity(x, y, box_normalized: bool = True):
    """(N,4) x (M,4) -> (N,M) IoU. ~ detection.py:765."""
    if _traced(x, y):
        from .detection_jit import iou_matrix
        return Tensor(iou_matrix(_jval(x), _jval(y), box_normalized))
    xa, ya = _arr(x).astype(np.float32), _arr(y).astype(np.float32)
    if not box_normalized:
        # unnormalized boxes count the boundary pixel (w = x2-x1+1)
        xa = xa.copy()
        ya = ya.copy()
        xa[:, 2:] += 1.0
        ya[:, 2:] += 1.0
    return Tensor(_arr(box_iou(Tensor(xa), Tensor(ya))))


def box_clip(input, im_info):
    """Clip (…,4) boxes to the ORIGINAL image extent. ~ detection.py:3057
    / box_clip_op.h: im_info is (H, W, scale) of the network input, and
    boxes clip to [0, round(W/scale)-1] x [0, round(H/scale)-1]."""
    if _traced(input, im_info):
        import jax.numpy as jnp

        from .detection_jit import clip_boxes
        return Tensor(clip_boxes(jnp.asarray(_jval(input)),
                                 jnp.asarray(_jval(im_info))))
    b = _arr(input).astype(np.float32)
    info = _arr(im_info).astype(np.float32).reshape(-1)
    scale = info[2] if info.size > 2 and info[2] > 0 else 1.0
    hmax = np.round(info[0] / scale) - 1.0
    wmax = np.round(info[1] / scale) - 1.0
    out = b.copy()
    out[..., 0::2] = np.clip(b[..., 0::2], 0.0, wmax)
    out[..., 1::2] = np.clip(b[..., 1::2], 0.0, hmax)
    return Tensor(out)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0):
    """SSD box encode/decode. ~ detection.py:819 / box_coder_op.cc.

    encode: target (N,4) corners vs priors (M,4) -> (N,M,4) offsets.
    decode: target (N,M,4) offsets + priors -> (N,M,4) corners
    (axis=0: priors broadcast over rows; axis=1: over columns).
    """
    if _traced(prior_box, prior_box_var, target_box):
        from .detection_jit import decode_center_size, encode_center_size
        pv = None if prior_box_var is None else _jval(prior_box_var)
        if code_type.startswith("encode"):
            return Tensor(encode_center_size(
                _jval(prior_box), pv, _jval(target_box), box_normalized))
        return Tensor(decode_center_size(
            _jval(prior_box), pv, _jval(target_box), axis,
            box_normalized))
    p = _arr(prior_box).astype(np.float32)
    t = _arr(target_box).astype(np.float32)
    pv = (None if prior_box_var is None
          else np.broadcast_to(_arr(prior_box_var).astype(np.float32),
                               p.shape))
    norm = 0.0 if box_normalized else 1.0
    pw = p[:, 2] - p[:, 0] + norm
    ph = p[:, 3] - p[:, 1] + norm
    pcx = p[:, 0] + pw * 0.5
    pcy = p[:, 1] + ph * 0.5
    if code_type.startswith("encode"):
        tw = t[:, 2] - t[:, 0] + norm
        th = t[:, 3] - t[:, 1] + norm
        tcx = t[:, 0] + tw * 0.5
        tcy = t[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = np.log(np.maximum(tw[:, None] / pw[None, :], 1e-10))
        oh = np.log(np.maximum(th[:, None] / ph[None, :], 1e-10))
        out = np.stack([ox, oy, ow, oh], -1)  # (N, M, 4)
        if pv is not None:
            out = out / pv[None, :, :]
        return Tensor(out.astype(np.float32))
    # decode
    if axis == 0:
        pw_, ph_, pcx_, pcy_ = (a[None, :] for a in (pw, ph, pcx, pcy))
        pv_ = None if pv is None else pv[None, :, :]
    else:
        pw_, ph_, pcx_, pcy_ = (a[:, None] for a in (pw, ph, pcx, pcy))
        pv_ = None if pv is None else pv[:, None, :]
    d = t if pv_ is None else t * pv_
    cx = d[..., 0] * pw_ + pcx_
    cy = d[..., 1] * ph_ + pcy_
    w = np.exp(d[..., 2]) * pw_
    h = np.exp(d[..., 3]) * ph_
    out = np.stack([cx - w * 0.5, cy - h * 0.5,
                    cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)
    return Tensor(out.astype(np.float32))


def prior_box(input, image, min_sizes: Sequence[float],
              max_sizes: Optional[Sequence[float]] = None,
              aspect_ratios: Sequence[float] = (1.0,),
              variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
              flip: bool = False, clip: bool = False,
              steps: Sequence[float] = (0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False):
    """SSD prior boxes over a feature map. ~ detection.py:1778 /
    prior_box_op.cc. Returns (boxes (H,W,P,4), variances (H,W,P,4)),
    normalized corner form."""
    fm = _arr(input)
    img = _arr(image)
    H, W = fm.shape[2], fm.shape[3]
    ih, iw = float(img.shape[2]), float(img.shape[3])
    step_h = steps[1] if steps[1] > 0 else ih / H
    step_w = steps[0] if steps[0] > 0 else iw / W

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs: List = []  # (w, h) per prior, in pixels
    for i, ms in enumerate(float(m) for m in min_sizes):
        sq = (np.sqrt(ms * float(max_sizes[i])),) * 2 if max_sizes \
            else None
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if sq:
                whs.append(sq)
            whs.extend((ms * np.sqrt(ar), ms / np.sqrt(ar))
                       for ar in ars if abs(ar - 1.0) >= 1e-6)
        else:
            whs.extend((ms * np.sqrt(ar), ms / np.sqrt(ar))
                       for ar in ars)
            if sq:
                whs.append(sq)
    P = len(whs)
    cxg, cyg = _cell_centers(H, W, step_w, step_h, offset)
    wh = np.asarray(whs, np.float32)                    # (P, 2)
    boxes = np.empty((H, W, P, 4), np.float32)
    boxes[..., 0] = (cxg[:, :, None] - wh[None, None, :, 0] / 2) / iw
    boxes[..., 1] = (cyg[:, :, None] - wh[None, None, :, 1] / 2) / ih
    boxes[..., 2] = (cxg[:, :, None] + wh[None, None, :, 0] / 2) / iw
    boxes[..., 3] = (cyg[:, :, None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    return Tensor(boxes), Tensor(_broadcast_var(variance, boxes.shape))


def _cell_centers(H, W, step_w, step_h, offset):
    """(H, W) grids of cell-center pixel coordinates."""
    cx = (np.arange(W, dtype=np.float32) + offset) * step_w
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h
    return np.meshgrid(cx, cy)


def _broadcast_var(variance, shape):
    return np.broadcast_to(np.asarray(variance, np.float32),
                           shape).copy()


def density_prior_box(input, image, densities: Sequence[int],
                      fixed_sizes: Sequence[float],
                      fixed_ratios: Sequence[float] = (1.0,),
                      variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                      clip: bool = False,
                      steps: Sequence[float] = (0.0, 0.0),
                      offset: float = 0.5):
    """Density prior boxes (face-detection SSD variant).
    ~ detection.py:1939 / density_prior_box_op.cc: each (density,
    fixed_size) pair lays a density x density sub-grid of shifted
    centers inside every cell, one box per fixed_ratio. Returns
    (boxes (H, W, P, 4), variances (H, W, P, 4)), normalized."""
    if len(densities) != len(fixed_sizes):
        raise ValueError(
            f"density_prior_box: densities ({len(densities)}) and "
            f"fixed_sizes ({len(fixed_sizes)}) must pair up 1:1")
    fm = _arr(input)
    img = _arr(image)
    H, W = fm.shape[2], fm.shape[3]
    ih, iw = float(img.shape[2]), float(img.shape[3])
    step_h = steps[1] if steps[1] > 0 else ih / H
    step_w = steps[0] if steps[0] > 0 else iw / W
    # the reference shifts the sub-grid by the INTEGER averaged step on
    # both axes (density_prior_box_op.cc step_average)
    step_avg = int(0.5 * (step_w + step_h))
    entries = []  # (dx, dy, w, h) center shift in px + box size
    for dens, fs in zip(densities, fixed_sizes):
        dens = int(dens)
        shift = int(step_avg / dens)
        for r in fixed_ratios:
            bw, bh = fs * np.sqrt(r), fs / np.sqrt(r)
            for di in range(dens):
                for dj in range(dens):
                    entries.append(((dj + 0.5) * shift - step_avg / 2.0,
                                    (di + 0.5) * shift - step_avg / 2.0,
                                    bw, bh))
    P = len(entries)
    e = np.asarray(entries, np.float32)                  # (P, 4)
    cxg, cyg = _cell_centers(H, W, step_w, step_h, offset)
    boxes = np.empty((H, W, P, 4), np.float32)
    ctrx = cxg[:, :, None] + e[None, None, :, 0]
    ctry = cyg[:, :, None] + e[None, None, :, 1]
    boxes[..., 0] = (ctrx - e[None, None, :, 2] / 2) / iw
    boxes[..., 1] = (ctry - e[None, None, :, 3] / 2) / ih
    boxes[..., 2] = (ctrx + e[None, None, :, 2] / 2) / iw
    boxes[..., 3] = (ctry + e[None, None, :, 3] / 2) / ih
    # the reference kernel clamps every corner to [0, 1] regardless of
    # the clip attr (density_prior_box_op.h); `clip` is kept for
    # signature parity only
    boxes = np.clip(boxes, 0.0, 1.0)
    return Tensor(boxes), Tensor(_broadcast_var(variance, boxes.shape))


def anchor_generator(input, anchor_sizes: Sequence[float],
                     aspect_ratios: Sequence[float],
                     variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                     stride: Sequence[float] = (16.0, 16.0),
                     offset: float = 0.5):
    """RPN anchors over a feature map (pixel coords, unnormalized).
    ~ detection.py:2413 / anchor_generator_op.cc. Returns
    (anchors (H,W,A,4), variances (H,W,A,4))."""
    fm = _arr(input)
    H, W = fm.shape[2], fm.shape[3]
    # Detectron-style anchors (anchor_generator_op.h): base w/h are ROUNDED
    # at stride scale then scaled by size/stride; ratios are the OUTER loop
    # (sizes inner) — the ordering must match or the 4A delta channels of a
    # reference-trained RPN head pair with the wrong anchors
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for ar in aspect_ratios:
        base_w = np.round(np.sqrt(sw * sh / ar))
        base_h = np.round(base_w * ar)
        for s in anchor_sizes:
            whs.append((float(s) / sw * base_w, float(s) / sh * base_h))
    A = len(whs)
    # centers: idx*stride + offset*(stride-1), corners at +/-0.5*(w-1)
    cxg = (np.arange(W, dtype=np.float32) * sw + offset * (sw - 1))[None, :]
    cyg = (np.arange(H, dtype=np.float32) * sh + offset * (sh - 1))[:, None]
    cxg = np.broadcast_to(cxg, (H, W))
    cyg = np.broadcast_to(cyg, (H, W))
    wh = np.asarray(whs, np.float32)
    anchors = np.empty((H, W, A, 4), np.float32)
    anchors[..., 0] = cxg[:, :, None] - (wh[None, None, :, 0] - 1) / 2
    anchors[..., 1] = cyg[:, :, None] - (wh[None, None, :, 1] - 1) / 2
    anchors[..., 2] = cxg[:, :, None] + (wh[None, None, :, 0] - 1) / 2
    anchors[..., 3] = cyg[:, :, None] + (wh[None, None, :, 1] - 1) / 2
    return Tensor(anchors), Tensor(_broadcast_var(variance,
                                                  anchors.shape))


def _greedy_nms(boxes, scores, thresh, norm, eta, max_keep=None):
    """Shared greedy suppression: ``norm`` 1.0 = the reference's
    unnormalized (+1 pixel) convention, 0.0 = normalized; ``eta`` < 1
    decays the threshold adaptively while it stays > 0.5. ``boxes``
    must be score-ordered already when scores is None."""
    order = np.arange(len(boxes)) if scores is None \
        else np.argsort(-scores)
    areas = ((boxes[:, 2] - boxes[:, 0] + norm)
             * (boxes[:, 3] - boxes[:, 1] + norm))
    keep, suppressed, th = [], np.zeros(len(boxes), bool), thresh
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        if max_keep is not None and len(keep) >= max_keep:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[:, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[:, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[:, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = (np.clip(xx2 - xx1 + norm, 0, None)
                 * np.clip(yy2 - yy1 + norm, 0, None))
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > th
        if eta < 1.0 and th > 0.5:
            th *= eta
    return keep


def detection_map(detections, gt_boxes, gt_labels, class_num: int,
                  overlap_threshold: float = 0.5,
                  ap_version: str = "integral"):
    """Mean average precision over ONE image set.
    ~ detection.py:1238 / detection_map_op (+ the DetectionMAP metric).

    detections: list per image of (K, 6) [label, score, x1, y1, x2, y2]
    rows (padding label -1 rows ignored — the multiclass_nms /
    detection_output contract); gt_boxes/gt_labels: lists per image.
    ap_version: 'integral' (VOC2010+) or '11point'. Returns float mAP.
    """
    aps = []
    for c in range(class_num):
        records = []  # (score, is_tp)
        n_gt = 0
        for det, gb, gl in zip(detections, gt_boxes, gt_labels):
            det = _arr(det)
            det = det[det[:, 0] == c]
            gb = _arr(gb).astype(np.float32).reshape(-1, 4)
            gl = _arr(gl).reshape(-1)
            gmask = gl == c
            gsel = gb[gmask]
            n_gt += len(gsel)
            used = np.zeros(len(gsel), bool)
            order = np.argsort(-det[:, 1])
            # one batched IoU matrix per (image, class) — a per-row
            # device dispatch would dominate eval time
            iou_all = (_arr(iou_similarity(det[order, 2:], gsel))
                       if len(gsel) and len(order) else None)
            for r, row in enumerate(det[order]):
                if iou_all is None:
                    records.append((row[1], False))
                    continue
                j = int(np.argmax(iou_all[r]))
                if iou_all[r, j] >= overlap_threshold and not used[j]:
                    used[j] = True
                    records.append((row[1], True))
                else:
                    records.append((row[1], False))
        if n_gt == 0:
            continue
        if not records:
            aps.append(0.0)
            continue
        records.sort(key=lambda r: -r[0])
        tp = np.cumsum([r[1] for r in records])
        fp = np.cumsum([not r[1] for r in records])
        recall = tp / n_gt
        precision = tp / np.maximum(tp + fp, 1)
        if ap_version == "11point":
            ap = float(np.mean([
                precision[recall >= t].max() if (recall >= t).any()
                else 0.0 for t in np.linspace(0, 1, 11)]))
        else:  # integral (VOC2010+): area under monotone envelope
            mrec = np.concatenate([[0.0], recall, [1.0]])
            mpre = np.concatenate([[0.0], precision, [0.0]])
            mpre = np.maximum.accumulate(mpre[::-1])[::-1]
            idx = np.nonzero(mrec[1:] != mrec[:-1])[0]
            ap = float(np.sum((mrec[idx + 1] - mrec[idx])
                              * mpre[idx + 1]))
        aps.append(ap)
    return float(np.mean(aps)) if aps else 0.0


def polygon_box_transform(input):
    """EAST-style quad decoding. ~ detection.py:970 /
    polygon_box_transform_op.cc: even geometry channels hold x offsets,
    odd channels y offsets, each against its pixel's coordinate on the
    4x-downsampled grid: out = 4*w - in (even) / 4*h - in (odd).
    Pure elementwise+iota — jit-able."""
    import jax.numpy as jnp

    from ..ops.dispatch import apply_op

    def fn(x):
        N, C, H, W = x.shape
        wgrid = jnp.arange(W, dtype=x.dtype) * 4.0
        hgrid = (jnp.arange(H, dtype=x.dtype) * 4.0)[:, None]
        even = jnp.arange(C)[:, None, None] % 2 == 0
        return jnp.where(even[None], wgrid - x, hgrid - x)

    return apply_op("polygon_box_transform", fn, input)


def bipartite_match(dist_matrix, match_type: str = "bipartite",
                    dist_threshold: float = 0.5):
    """Greedy bipartite matching. ~ detection.py:1331 /
    bipartite_match_op.cc. dist_matrix (G, P): similarity of each
    ground-truth row to each prior column. Returns
    (match_indices (P,) int32 — gt index per prior or -1,
     match_dist (P,) f32).

    'bipartite': iteratively take the global argmax, retiring its row
    and column (each gt matches its best unclaimed prior).
    'per_prediction': additionally match every unmatched prior to its
    best gt when that similarity > dist_threshold (the SSD recipe).
    """
    d = _arr(dist_matrix).astype(np.float32).copy()
    G, P = d.shape
    match_idx = np.full((P,), -1, np.int32)
    match_dist = np.zeros((P,), np.float32)
    if G == 0:  # no ground truth: nothing matches (negatives-only image)
        return Tensor(match_idx), Tensor(match_dist)
    work = d.copy()
    for _ in range(min(G, P)):
        g, p = np.unravel_index(np.argmax(work), work.shape)
        if work[g, p] <= 0:
            break
        match_idx[p] = g
        match_dist[p] = d[g, p]
        work[g, :] = -1.0
        work[:, p] = -1.0
    if match_type == "per_prediction":
        best_gt = np.argmax(d, axis=0)
        best_dist = d[best_gt, np.arange(P)]
        extra = (match_idx < 0) & (best_dist > dist_threshold)
        match_idx[extra] = best_gt[extra]
        match_dist[extra] = best_dist[extra]
    return Tensor(match_idx), Tensor(match_dist)


def target_assign(input, match_indices, mismatch_value=0):
    """Scatter per-gt rows to priors by match index.
    ~ detection.py:1421 / target_assign_op.h. input (G, K),
    match_indices (P,) -> (out (P, K), weight (P, 1)); unmatched priors
    get mismatch_value with weight 0."""
    x = _arr(input).astype(np.float32)
    mi = _arr(match_indices).astype(np.int64)
    P = mi.shape[0]
    out = np.full((P, x.shape[1]), float(mismatch_value), np.float32)
    w = np.zeros((P, 1), np.float32)
    matched = mi >= 0
    out[matched] = x[mi[matched]]
    w[matched] = 1.0
    return Tensor(out), Tensor(w)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label: int = 0,
             overlap_threshold: float = 0.5, neg_pos_ratio: float = 3.0,
             loc_loss_weight: float = 1.0, conf_loss_weight: float = 1.0):
    """The SSD multibox training loss for ONE image. ~ detection.py:1527
    / the MultiBoxLoss recipe: per_prediction matching, localization
    smooth-L1 on matched priors against box_coder-encoded offsets, and
    softmax confidence loss with 3:1 hard negative mining.

    location (P, 4) predicted offsets; confidence (P, C) logits;
    gt_box (G, 4); gt_label (G,) int (values in [1, C));
    prior_box (P, 4), prior_box_var (P, 4) or None. Returns scalar
    Tensor (differentiable through location/confidence).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply_op

    pb = _arr(prior_box).astype(np.float32)
    gtb = _arr(gt_box).astype(np.float32)
    gtl = _arr(gt_label).astype(np.int64).reshape(-1)
    P = pb.shape[0]

    # host-side matching + target construction (no gradients flow here)
    iou = _arr(iou_similarity(gtb, pb))                     # (G, P)
    mi, _ = bipartite_match(iou, "per_prediction", overlap_threshold)
    mi = _arr(mi)
    enc = _arr(box_coder(pb, prior_box_var, gtb,
                         "encode_center_size"))             # (G, P, 4)
    matched = mi >= 0
    loc_target = np.zeros((P, 4), np.float32)
    loc_target[matched] = enc[mi[matched], np.arange(P)[matched]]
    conf_target = np.full((P,), background_label, np.int64)
    conf_target[matched] = gtl[mi[matched]]
    n_pos = max(int(matched.sum()), 1)
    n_neg_keep = int(min(neg_pos_ratio * n_pos, P - n_pos))

    def fused(loc, conf, loc_t, conf_t, pos_mask):
        logp = jax.nn.log_softmax(conf.astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(logp, conf_t[:, None], -1)[:, 0]  # (P,)
        # hard negative mining: EXACTLY the top-k background CE among
        # negatives (a >=-threshold rule would keep every tied negative
        # — with a fresh zero-init head that is ALL of them)
        neg_ce = jnp.where(pos_mask, -jnp.inf, ce)
        if n_neg_keep > 0:
            _, neg_idx = jax.lax.top_k(neg_ce, n_neg_keep)
            neg_keep = jnp.zeros_like(pos_mask).at[neg_idx].set(True)
        else:
            neg_keep = jnp.zeros_like(pos_mask)
        conf_loss = jnp.sum(jnp.where(pos_mask | neg_keep, ce, 0.0))
        diff = jnp.abs((loc - loc_t).astype(jnp.float32))
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
        loc_loss = jnp.sum(jnp.where(pos_mask[:, None], sl1, 0.0))
        return ((conf_loss_weight * conf_loss
                 + loc_loss_weight * loc_loss) / n_pos)

    return apply_op("ssd_loss", fused, location, confidence,
                    Tensor(loc_target), Tensor(conf_target),
                    Tensor(matched))


def rpn_target_assign(anchor_box, anchor_var, gt_boxes, im_info,
                      rpn_batch_size_per_im: int = 256,
                      rpn_straddle_thresh: float = 0.0,
                      rpn_fg_fraction: float = 0.5,
                      rpn_positive_overlap: float = 0.7,
                      rpn_negative_overlap: float = 0.3,
                      use_random: bool = True, rng=None):
    """RPN anchor sampling + targets for ONE image (Faster-RCNN recipe).
    ~ detection.py:312 / rpn_target_assign_op.cc. Positives: each gt's
    best-IoU anchor plus any anchor with IoU > rpn_positive_overlap;
    negatives: IoU < rpn_negative_overlap everywhere; both subsampled to
    rpn_batch_size_per_im at rpn_fg_fraction. Anchors straddling the
    image border by more than rpn_straddle_thresh px are excluded.

    Returns (loc_index (F,), score_index (F+B,), tgt_bbox (F,4) encoded,
    tgt_label (F+B,) {1,0}) — index tensors into the M anchors, the
    reference's gather-style training contract.
    """
    an = _arr(anchor_box).astype(np.float32).reshape(-1, 4)
    av = (None if anchor_var is None
          else _arr(anchor_var).astype(np.float32).reshape(-1, 4))
    gtb = _arr(gt_boxes).astype(np.float32).reshape(-1, 4)
    info = _arr(im_info).astype(np.float32).reshape(-1)
    M = an.shape[0]
    # fresh entropy by default (a fixed default seed would drop the SAME
    # negatives every call, defeating random subsampling); pass an int
    # or Generator for reproducibility
    if not isinstance(rng, np.random.Generator):
        rng = np.random.default_rng(rng)

    if rpn_straddle_thresh >= 0:
        t = rpn_straddle_thresh
        inside = ((an[:, 0] >= -t) & (an[:, 1] >= -t)
                  & (an[:, 2] < info[1] + t) & (an[:, 3] < info[0] + t))
    else:
        inside = np.ones(M, bool)
    cand = np.nonzero(inside)[0]

    labels = np.full(M, -1, np.int64)  # -1 ignore, 0 neg, 1 pos
    assigned_gt = np.zeros(M, np.int64)
    if len(gtb) and len(cand):
        # pixel-coordinate anchors use the +1 (unnormalized) IoU
        # convention, matching generate_proposals and the reference op
        iou = _arr(iou_similarity(gtb, an[cand],
                                  box_normalized=False))   # (G, C)
        best_per_anchor = iou.max(axis=0)
        assigned_gt[cand] = iou.argmax(axis=0)
        labels[cand[best_per_anchor >= rpn_positive_overlap]] = 1
        # each gt's best anchor(s) are positive even below the
        # threshold — ALL ties share the max (symmetric grids tie often)
        gt_max = iou.max(axis=1, keepdims=True)
        labels[cand[((iou >= gt_max - 1e-6) & (gt_max > 0)).any(axis=0)]] \
            = 1
        labels[cand[(best_per_anchor < rpn_negative_overlap)
                    & (labels[cand] != 1)]] = 0
    elif len(cand):
        labels[cand] = 0  # no gt: all inside anchors are negatives

    n_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
    fg = np.nonzero(labels == 1)[0]
    if len(fg) > n_fg:
        drop = (rng.choice(fg, len(fg) - n_fg, replace=False)
                if use_random else fg[n_fg:])
        labels[drop] = -1
        fg = np.nonzero(labels == 1)[0]
    n_bg = rpn_batch_size_per_im - len(fg)
    bg = np.nonzero(labels == 0)[0]
    if len(bg) > n_bg:
        drop = (rng.choice(bg, len(bg) - n_bg, replace=False)
                if use_random else bg[n_bg:])
        labels[drop] = -1
        bg = np.nonzero(labels == 0)[0]

    tgt = np.zeros((len(fg), 4), np.float32)
    if len(fg) and len(gtb):
        enc = _arr(box_coder(an[fg], av[fg] if av is not None else None,
                             gtb, "encode_center_size"))   # (G, F, 4)
        tgt = enc[assigned_gt[fg], np.arange(len(fg))]
    score_index = np.concatenate([fg, bg])
    tgt_label = np.concatenate([np.ones(len(fg), np.int64),
                                np.zeros(len(bg), np.int64)])
    return (Tensor(fg.astype(np.int64)),
            Tensor(score_index.astype(np.int64)),
            Tensor(tgt), Tensor(tgt_label))


from .. import nn as _nn  # noqa: E402  (nn loads before vision)


class MultiBoxHead(_nn.Layer):
    """SSD multi-feature-map head. ~ detection.py:2120 (fluid
    multi_box_head): one (loc, conf) conv pair per feature map + its
    prior boxes, flattened and concatenated in matching prior order —
    the glue between a backbone pyramid and ssd_loss/detection_output.
    A real nn.Layer: parameters register with the parent model's
    optimizer/state_dict. (static/nn.py's multi_box_head is the
    declarative-mode sibling with its own fluid-faithful prior
    counting; this class is the canonical eager implementation.)

    forward(inputs, image) -> (mbox_locs (B, P, 4), mbox_confs
    (B, P, num_classes), priors (P, 4) normalized, variances (P, 4)).
    Priors are cached per feature/image shape tuple.
    """

    def __init__(self, num_classes, min_sizes, max_sizes=None,
                 aspect_ratios=None, in_channels=None,
                 variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                 clip=False, steps=None, offset=0.5):
        super().__init__()
        n_maps = len(min_sizes)
        if in_channels is None:
            raise ValueError("MultiBoxHead needs in_channels (one per "
                             "feature map) to build its convs")
        if aspect_ratios is None:
            aspect_ratios = [[2.0]] * n_maps
        # fluid accepts scalar per-map ratios (aspect_ratios=[2., 3.])
        aspect_ratios = [list(a) if isinstance(a, (list, tuple))
                         else [float(a)] for a in aspect_ratios]
        steps = list(steps) if steps else [0.0] * n_maps
        for name, seq in (("in_channels", in_channels),
                          ("aspect_ratios", aspect_ratios),
                          ("steps", steps)):
            if len(seq) != n_maps:
                raise ValueError(
                    f"MultiBoxHead: {name} has {len(seq)} entries for "
                    f"{n_maps} feature maps")
        if max_sizes is not None and len(max_sizes) != n_maps:
            raise ValueError(
                f"MultiBoxHead: max_sizes has {len(max_sizes)} entries "
                f"for {n_maps} feature maps")
        self.num_classes = num_classes
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes) if max_sizes else None
        self.aspect_ratios = aspect_ratios
        self.variance = tuple(variance)
        self.flip = flip
        self.clip = clip
        self.steps = steps
        self.offset = offset
        self._prior_cache = {}
        self.loc_convs = _nn.LayerList()
        self.conf_convs = _nn.LayerList()
        self._prior_counts = []
        for i, cin in enumerate(in_channels):
            p = self._n_priors(i)
            self._prior_counts.append(p)
            self.loc_convs.append(_nn.Conv2D(cin, p * 4, 3, padding=1))
            self.conf_convs.append(_nn.Conv2D(cin, p * num_classes, 3,
                                              padding=1))

    def _n_priors(self, i: int) -> int:
        # derived by running the REAL prior generator on a 1x1 map, so
        # the conv channel counts can never desync from prior_box's
        # counting rules
        mx = [self.max_sizes[i]] if self.max_sizes else None
        boxes, _ = prior_box(np.zeros((1, 1, 1, 1), np.float32),
                             np.zeros((1, 1, 8, 8), np.float32),
                             [self.min_sizes[i]], mx,
                             self.aspect_ratios[i], self.variance,
                             self.flip, self.clip)
        return boxes.shape[2]

    def _priors_for(self, i, fm, image):
        key = (i, tuple(fm.shape[2:]), tuple(image.shape[2:]))
        if key not in self._prior_cache:
            mx = [self.max_sizes[i]] if self.max_sizes else None
            boxes, v = prior_box(
                fm, image, [self.min_sizes[i]], mx,
                self.aspect_ratios[i], self.variance, self.flip,
                self.clip, (self.steps[i], self.steps[i]), self.offset)
            self._prior_cache[key] = (_arr(boxes).reshape(-1, 4),
                                      _arr(v).reshape(-1, 4))
        return self._prior_cache[key]

    def forward(self, inputs, image):
        from ..ops.manipulation import concat
        if len(inputs) != len(self.loc_convs):
            raise ValueError(
                f"MultiBoxHead built for {len(self.loc_convs)} feature "
                f"maps, got {len(inputs)}")
        locs, confs, pri, var = [], [], [], []
        for i, fm in enumerate(inputs):
            p = self._prior_counts[i]  # fixed at __init__
            loc_map = self.loc_convs[i](fm)
            conf_map = self.conf_convs[i](fm)
            B = loc_map.shape[0]
            # (B, p*4, H, W) -> (B, H, W, p*4) -> (B, H*W*p, 4):
            # matches prior_box's (H, W, P, 4) flatten order
            H, W = loc_map.shape[2], loc_map.shape[3]
            locs.append(loc_map.transpose([0, 2, 3, 1])
                        .reshape([B, H * W * p, 4]))
            confs.append(conf_map.transpose([0, 2, 3, 1])
                         .reshape([B, H * W * p, self.num_classes]))
            pb, pv = self._priors_for(i, fm, image)
            pri.append(pb)
            var.append(pv)
        return (concat(locs, axis=1), concat(confs, axis=1),
                Tensor(np.concatenate(pri)), Tensor(np.concatenate(var)))


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label: int = 0,
                     nms_threshold: float = 0.3, nms_top_k: int = 400,
                     keep_top_k: int = 200,
                     score_threshold: float = 0.01, nms_eta: float = 1.0):
    """SSD inference head: decode + multiclass NMS for ONE image.
    ~ detection.py:622 / detection_output_op: loc (P, 4) offsets against
    priors, scores (P, C) softmax probabilities. Returns the
    multiclass_nms fixed-size contract: (out (keep_top_k, 6), count)."""
    p = _arr(prior_box).astype(np.float32)
    pv = None if prior_box_var is None else _arr(prior_box_var)
    d = _arr(loc).astype(np.float32)
    boxes = np.array(_arr(box_coder(p, pv, d[None],
                                    "decode_center_size", axis=0))[0])
    s = _arr(scores).astype(np.float32)
    out, counts = multiclass_nms(
        boxes[None], s.T[None], score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, nms_eta=nms_eta,
        background_label=background_label)
    return (Tensor(_arr(out)[0]),
            Tensor(np.asarray(_arr(counts)[0], np.int32)))


def retinanet_target_assign(anchor_box, anchor_var, gt_boxes, gt_labels,
                            im_info, positive_overlap: float = 0.5,
                            negative_overlap: float = 0.4, rng=None):
    """RetinaNet anchor targets. ~ detection.py:71 /
    retinanet_target_assign_op: the RPN assignment rule with (a) NO
    fg/bg subsampling (focal loss handles imbalance) and (b) per-class
    fg labels instead of binary objectness.

    Returns (loc_index (F,), score_index (F+B,), tgt_bbox (F, 4),
    tgt_label (F+B,) — gt class for fg, 0 for bg)."""
    gtl = _arr(gt_labels).astype(np.int64).reshape(-1)
    an = _arr(anchor_box).astype(np.float32).reshape(-1, 4)
    gtb = _arr(gt_boxes).astype(np.float32).reshape(-1, 4)
    fg, score_idx, tgt_bbox, _ = rpn_target_assign(
        anchor_box, anchor_var, gt_boxes, im_info,
        rpn_batch_size_per_im=len(an) + len(gtb) + 1,  # no subsampling
        rpn_fg_fraction=1.0,       # ...of positives either
        rpn_straddle_thresh=-1.0,  # RetinaNet keeps border anchors
        rpn_positive_overlap=positive_overlap,
        rpn_negative_overlap=negative_overlap, rng=rng)
    fg_a = _arr(fg)
    labels = np.zeros(len(_arr(score_idx)), np.int64)
    if len(fg_a) and len(gtb):
        iou = _arr(iou_similarity(gtb, an[fg_a], box_normalized=False))
        labels[:len(fg_a)] = gtl[iou.argmax(axis=0)]
    return fg, score_idx, tgt_bbox, Tensor(labels)


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip: float):
    """Cascade-RCNN per-class decode + best-class assignment.
    ~ detection.py:3811 / box_decoder_and_assign_op.h: prior (R, 4)
    unnormalized (+1 widths), target (R, 4*C) per-class offsets scaled
    by the SHARED 4-vector prior_box_var, dw/dh clipped at box_clip.
    Returns (decode_box (R, 4*C), assign_box (R, 4) — the decoded box
    of each roi's best NON-background class, or the prior itself when
    no foreground class wins).
    """
    p = _arr(prior_box).astype(np.float32).reshape(-1, 4)
    pv = _arr(prior_box_var).astype(np.float32).reshape(4)
    t = _arr(target_box).astype(np.float32)
    s = _arr(box_score).astype(np.float32)
    R, C = s.shape
    # pre-scale by the shared variance and clip dw/dh, then the shared
    # decode (box_coder) owns the center-size math
    d = t.reshape(R, C, 4) * pv
    d[..., 2:] = np.minimum(d[..., 2:], box_clip)
    dec = np.array(_arr(box_coder(p, None, d, "decode_center_size",
                                  box_normalized=False, axis=1)))
    # best foreground class per roi (class 0 is background); the reference
    # assigns the best non-background class's decoded box — its only gate
    # is the max_score = -1 initializer with a strict '>', so the prior
    # wins only when every fg score is <= -1 (raw-logit callers)
    # (box_decoder_and_assign_op.h:77-97)
    if C > 1:
        fg = s[:, 1:]
        best = fg.argmax(axis=1) + 1
        has_fg = fg.max(axis=1) > -1
        assign = np.where(has_fg[:, None], dec[np.arange(R), best], p)
    else:
        assign = p
    return Tensor(dec.reshape(R, C * 4)), Tensor(assign.astype(
        np.float32))


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n: int = 6000,
                       post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1,
                       eta: float = 1.0, return_rois_num: bool = False):
    """Faster-RCNN RPN proposals. ~ detection.py:2908 /
    generate_proposals_op.cc: decode RPN deltas against anchors, clip to
    the network input, drop tiny boxes, per-image top-pre_nms_top_n +
    NMS. TPU-side contract: rois come back FIXED-size
    (N, post_nms_top_n, 4) zero-padded with per-image counts.

    scores (N, A, H, W); bbox_deltas (N, 4A, H, W); im_info (N, 3)
    rows (H_in, W_in, scale); anchors/variances (H, W, A, 4) unnormalized
    corner form (anchor_generator output).
    """
    sc = _arr(scores).astype(np.float32)
    bd = _arr(bbox_deltas).astype(np.float32)
    info = _arr(im_info).astype(np.float32).reshape(-1, 3)
    an = _arr(anchors).astype(np.float32).reshape(-1, 4)
    var = _arr(variances).astype(np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    K = post_nms_top_n
    rois = np.zeros((N, K, 4), np.float32)
    counts = np.zeros((N,), np.int32)
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # (H*W*A,)
        d = bd[n].reshape(A, 4, H, W).transpose(
            2, 3, 0, 1).reshape(-1, 4)                    # (H*W*A, 4)
        # decode (box_coder decode semantics, one delta per anchor)
        dec = np.array(_arr(box_coder(an, var, d[:, None, :],
                                      "decode_center_size", axis=1))[:, 0])
        hmax, wmax = info[n, 0] - 1.0, info[n, 1] - 1.0
        dec[:, 0::2] = np.clip(dec[:, 0::2], 0.0, wmax)
        dec[:, 1::2] = np.clip(dec[:, 1::2], 0.0, hmax)
        ms = max(min_size, 1.0) * (info[n, 2] if info[n, 2] > 0 else 1.0)
        wh = dec[:, 2:] - dec[:, :2] + 1.0
        valid = (wh >= ms).all(axis=1)
        idx = np.nonzero(valid)[0]
        if len(idx) == 0:
            continue
        order = idx[np.argsort(-s[idx])][:int(pre_nms_top_n)]
        boxes = dec[order]  # score-sorted already
        keep = _greedy_nms(boxes, None, nms_thresh, 1.0, eta,
                           max_keep=K)
        rois[n, :len(keep)] = boxes[keep]
        counts[n] = len(keep)
    # return_rois_num kept for signature parity; the fixed-size contract
    # always needs the counts, so both forms return them
    return Tensor(rois), Tensor(counts)


def generate_proposal_labels(rpn_rois, gt_classes, gt_boxes, im_info,
                             batch_size_per_im: int = 256,
                             fg_fraction: float = 0.25,
                             fg_thresh: float = 0.5,
                             bg_thresh_hi: float = 0.5,
                             bg_thresh_lo: float = 0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums: int = 81,
                             use_random: bool = True, rng=None):
    """Sample RoIs + build RCNN-head targets for ONE image.
    ~ detection.py:2610 / generate_proposal_labels_op.cc: gt boxes join
    the candidate set, fg = IoU >= fg_thresh (sampled to fg_fraction),
    bg = IoU in [bg_thresh_lo, bg_thresh_hi), targets are per-class
    box_coder offsets with bbox_reg_weights as inverse variances.

    Returns (rois (R,4) in ORIGINAL-image coords, labels (R,) int64,
    bbox_targets (R, 4*C), bbox_inside_weights (R, 4*C),
    bbox_outside_weights (R, 4*C)).
    """
    if not isinstance(rng, np.random.Generator):
        rng = np.random.default_rng(rng)
    rois = _arr(rpn_rois).astype(np.float32).reshape(-1, 4)
    gtb = _arr(gt_boxes).astype(np.float32).reshape(-1, 4)
    gtc = _arr(gt_classes).astype(np.int64).reshape(-1)
    info = _arr(im_info).astype(np.float32).reshape(-1)
    # rpn rois are in network-input coords, gt boxes in original-image
    # coords (the reference divides by im_scale before joining them)
    scale = info[2] if info.size > 2 and info[2] > 0 else 1.0
    rois = rois / scale
    cand = np.concatenate([rois, gtb]) if len(gtb) else rois

    if len(gtb):
        iou = _arr(iou_similarity(gtb, cand,
                                  box_normalized=False))  # (G, R+G)
        best = iou.max(axis=0)
        best_gt = iou.argmax(axis=0)
    else:
        best = np.zeros(len(cand), np.float32)
        best_gt = np.zeros(len(cand), np.int64)

    fg_all = np.nonzero(best >= fg_thresh)[0]
    bg_all = np.nonzero((best < bg_thresh_hi)
                        & (best >= bg_thresh_lo))[0]
    n_fg = min(int(fg_fraction * batch_size_per_im), len(fg_all))
    fg = (rng.choice(fg_all, n_fg, replace=False) if use_random
          else fg_all[:n_fg]) if len(fg_all) else fg_all
    n_bg = min(batch_size_per_im - n_fg, len(bg_all))
    bg = (rng.choice(bg_all, n_bg, replace=False) if use_random
          else bg_all[:n_bg]) if len(bg_all) else bg_all

    keep = np.concatenate([fg, bg]).astype(np.int64)
    out_rois = cand[keep]
    labels = np.zeros(len(keep), np.int64)
    labels[:len(fg)] = gtc[best_gt[fg]] if len(fg) else labels[:0]

    targets = np.zeros((len(keep), 4 * class_nums), np.float32)
    inside_w = np.zeros_like(targets)
    if len(fg):
        enc = _arr(box_coder(cand[fg],
                             np.asarray(bbox_reg_weights, np.float32),
                             gtb, "encode_center_size"))
        off = enc[best_gt[fg], np.arange(len(fg))]       # (F, 4)
        for r, c in enumerate(labels[:len(fg)]):
            targets[r, 4 * c:4 * c + 4] = off[r]
            inside_w[r, 4 * c:4 * c + 4] = 1.0
    # outside weights equal inside in the standard recipe (loss
    # normalization happens in the loss, not here) — kept as a separate
    # output for the reference's 5-tuple contract
    return (Tensor(out_rois), Tensor(labels), Tensor(targets),
            Tensor(inside_w), Tensor(inside_w.copy()))


def distribute_fpn_proposals(fpn_rois, min_level: int, max_level: int,
                             refer_level: int, refer_scale: float,
                             rois_num=None):
    """Assign RoIs to FPN levels by scale. ~ detection.py (fluid
    distribute_fpn_proposals / distribute_fpn_proposals_op.cc):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)),
    clamped to [min_level, max_level]. Returns (list of per-level RoI
    arrays, restore_index (R,) mapping concatenated-level order back to
    the input order)."""
    if rois_num is not None:
        raise NotImplementedError(
            "distribute_fpn_proposals: batched rois_num is not supported"
            " — call per image (generate_proposals' fixed-size output "
            "makes per-image slicing trivial)")
    r = _arr(fpn_rois).astype(np.float32).reshape(-1, 4)
    w = np.maximum(r[:, 2] - r[:, 0], 0.0)
    h = np.maximum(r[:, 3] - r[:, 1], 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(refer_level + np.log2(
        np.maximum(scale, 1e-6) / refer_scale))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, order = [], []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(Tensor(r[idx]))
        order.append(idx)
    restore = np.argsort(np.concatenate(order))
    return outs, Tensor(restore.astype(np.int64))


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold: float = 0.05,
                               nms_top_k: int = 1000,
                               keep_top_k: int = 100,
                               nms_threshold: float = 0.45,
                               nms_eta: float = 1.0):
    """RetinaNet inference head for ONE image. ~ detection.py:3120 /
    retinanet_detection_output_op.cc: per-FPN-level sigmoid scores are
    thresholded and top-nms_top_k decoded against that level's anchors;
    merged candidates then go through class-wise NMS + keep_top_k
    (fixed-size padded output, as multiclass_nms here).

    bboxes: list of (Mi, 4) per-level deltas; scores: list of (Mi, C)
    per-level sigmoid scores; anchors: list of (Mi, 4) per-level
    anchors (unnormalized corners). Returns (out (keep_top_k, 6),
    count () int32) with [label, score, x1, y1, x2, y2] rows.
    """
    info = _arr(im_info).astype(np.float32).reshape(-1)
    var = np.asarray([1.0, 1.0, 1.0, 1.0], np.float32)
    cand_boxes, cand_scores = [], []
    for lb, ls, la in zip(bboxes, scores, anchors):
        d = _arr(lb).astype(np.float32)
        s = _arr(ls).astype(np.float32)
        a = _arr(la).astype(np.float32)
        # keep this level's top-nms_top_k candidate (box, class) pairs
        flat = s.reshape(-1)
        mask = flat > score_threshold
        if not mask.any():
            continue
        idx = np.nonzero(mask)[0]
        if nms_top_k > 0 and len(idx) > nms_top_k:
            idx = idx[np.argsort(-flat[idx])[:nms_top_k]]
        bi, ci = np.unravel_index(idx, s.shape)
        dec = np.array(_arr(box_coder(
            a[bi], var, d[bi][:, None, :], "decode_center_size",
            axis=1))[:, 0])
        hmax, wmax = info[0] - 1.0, info[1] - 1.0
        dec[:, 0::2] = np.clip(dec[:, 0::2], 0.0, wmax)
        dec[:, 1::2] = np.clip(dec[:, 1::2], 0.0, hmax)
        cand_boxes.append(dec)
        cand_scores.append(np.stack([ci.astype(np.float32),
                                     flat[idx]], 1))
    out = np.full((int(keep_top_k), 6), -1.0, np.float32)
    if not cand_boxes:
        return Tensor(out), Tensor(np.zeros((), np.int32))
    boxes = np.concatenate(cand_boxes)
    cls_sc = np.concatenate(cand_scores)
    dets = []
    for c in np.unique(cls_sc[:, 0]):
        m = cls_sc[:, 0] == c
        mb, ms = boxes[m], cls_sc[m, 1]
        dets.extend((c, ms[k], mb[k])
                    for k in _greedy_nms(mb, ms, nms_threshold,
                                         1.0, nms_eta))
    dets.sort(key=lambda d: -d[1])
    dets = dets[:int(keep_top_k)]
    for r, (c, sc, box) in enumerate(dets):
        out[r, 0], out[r, 1], out[r, 2:] = c, sc, box
    return Tensor(out), Tensor(np.asarray(len(dets), np.int32))


def locality_aware_nms(bboxes, scores, score_threshold: float,
                       nms_top_k: int = -1, keep_top_k: int = -1,
                       nms_threshold: float = 0.3,
                       normalized: bool = True):
    """Locality-aware NMS (EAST OCR). ~ detection.py:3430 /
    locality_aware_nms_op.cc: a linear pre-pass MERGES consecutive
    overlapping boxes unconditionally by score-weighted averaging
    (accumulating the scores) — score_threshold applies only to the
    accumulated post-merge scores — then standard per-class greedy NMS
    runs on the merged set. The box array is SHARED and mutated across
    classes (the reference's bbox_slice aliases the input), so class
    c > 0 merges against boxes already merged by earlier classes, and
    the output gathers box coordinates after ALL classes ran.

    bboxes (1, M, 4), scores (1, C, M) (batch 1, as the reference op
    enforces) -> out (1, keep_top_k, 6) padded with -1 when
    keep_top_k >= 0 (0 keeps nothing, as the reference's
    `keep_top_k > -1` resize does); keep_top_k < 0 returns the exact
    kept set (data-dependent width). counts (1,) int32.
    """
    barr = _arr(bboxes).astype(np.float32)
    sarr = _arr(scores).astype(np.float32)
    if barr.shape[0] != 1 or sarr.shape[0] != 1:
        raise ValueError("locality_aware_nms supports batch 1 (got "
                         f"{barr.shape[0]}) — the reference op contract")
    b, s = barr[0].copy(), sarr[0]
    C, M = s.shape
    norm = 0.0 if normalized else 1.0

    def _iou1(a, c):
        x1, y1 = max(a[0], c[0]), max(a[1], c[1])
        x2, y2 = min(a[2], c[2]), min(a[3], c[3])
        inter = max(0.0, x2 - x1 + norm) * max(0.0, y2 - y1 + norm)
        aa = (a[2] - a[0] + norm) * (a[3] - a[1] + norm)
        ac = (c[2] - c[0] + norm) * (c[3] - c[1] + norm)
        return inter / (aa + ac - inter + 1e-10)

    picked = []  # (class, score, box_index) — boxes gathered at the end
    for c in range(C):
        sc = s[c].copy()
        skip = np.ones(M, bool)
        index = -1
        for i in range(M):
            if index > -1:
                if _iou1(b[i], b[index]) > nms_threshold:
                    # PolyWeightedMerge: merge box i INTO slot `index`
                    # of the shared array; scores accumulate
                    w1, w2 = sc[i], sc[index]
                    b[index] = (b[i] * w1 + b[index] * w2) / (w1 + w2)
                    sc[index] += sc[i]
                else:
                    skip[index] = False
                    index = i
            else:
                index = i
        if index > -1:
            skip[index] = False
        cand = np.nonzero((sc > score_threshold) & ~skip)[0]
        if len(cand) == 0:
            continue
        order = cand[np.argsort(-sc[cand], kind="stable")]
        if nms_top_k > -1 and len(order) > nms_top_k:
            order = order[:nms_top_k]
        for k in _greedy_nms(b[order], None, nms_threshold, norm, 1.0):
            picked.append((c, float(sc[order[k]]), int(order[k])))

    picked.sort(key=lambda d: -d[1])
    if keep_top_k > -1:
        picked = picked[:int(keep_top_k)]
    K = int(keep_top_k) if keep_top_k >= 0 else len(picked)
    out = np.full((1, K, 6), -1.0, np.float32)
    for r, (c, sv, bi) in enumerate(picked):
        out[0, r, 0], out[0, r, 1], out[0, r, 2:] = c, sv, b[bi]
    return Tensor(out), Tensor(np.asarray([len(picked)], np.int32))


def matrix_nms(bboxes, scores, score_threshold: float, post_threshold:
               float = 0.0, nms_top_k: int = 400, keep_top_k: int = 200,
               use_gaussian: bool = False, gaussian_sigma: float = 2.0,
               background_label: int = 0, normalized: bool = True):
    """Matrix NMS (SOLOv2) — the closed-form soft-NMS.
    ~ paddle.vision.ops.matrix_nms / matrix_nms_op.cc. Unlike greedy
    NMS, the decay of every box is a pure matrix expression over the
    pairwise IoUs of higher-scored boxes — no sequential suppression
    loop — so THIS nms runs on the TPU inside jit (the serving-side
    NMS for compiled detection heads; greedy variants here are host
    ops).

    bboxes (N, M, 4), scores (N, C, M) -> (out (N, keep_top_k, 6)
    [label, score, box] rows padded with -1, counts (N,)).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply_op

    norm = 0.0 if normalized else 1.0
    C_idx = background_label

    def one_class(boxes, sc):
        """boxes (M, 4), sc (M,) -> decayed scores (M,)."""
        order = jnp.argsort(-sc)
        b = boxes[order]
        s = sc[order]
        area = ((b[:, 2] - b[:, 0] + norm)
                * (b[:, 3] - b[:, 1] + norm))
        x1 = jnp.maximum(b[:, None, 0], b[None, :, 0])
        y1 = jnp.maximum(b[:, None, 1], b[None, :, 1])
        x2 = jnp.minimum(b[:, None, 2], b[None, :, 2])
        y2 = jnp.minimum(b[:, None, 3], b[None, :, 3])
        inter = (jnp.clip(x2 - x1 + norm, 0, None)
                 * jnp.clip(y2 - y1 + norm, 0, None))
        iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)
        # iou[i, j] for i < j (i scored higher): upper triangle
        iou = jnp.triu(iou, k=1)
        # compensation: how much row i was ITSELF overlapped by boxes
        # above it (max over k<i of iou[k, i]) — broadcast along rows
        comp = jnp.max(iou, axis=0)[:, None]
        if use_gaussian:
            decay = jnp.exp(-(jnp.square(iou) - jnp.square(comp))
                            / gaussian_sigma)
        else:
            decay = (1.0 - iou) / jnp.maximum(1.0 - comp, 1e-10)
        # decay only applies from higher-scored rows
        decay = jnp.where(jnp.triu(jnp.ones_like(iou), k=1) > 0,
                          decay, 1.0)
        dec = jnp.min(decay, axis=0) * s
        # un-sort back to input order
        out = jnp.zeros_like(sc).at[order].set(dec)
        return out

    def fn(b, s):
        N, C, M = s.shape
        mask = s > score_threshold
        s_in = jnp.where(mask, s, 0.0)
        # per-class top-nms_top_k pre-filter (bounds the O(k^2) decay
        # matrix and matches the reference's pre-decay drop; nms_util.h
        # truncates whenever top_k > -1, so 0 keeps nothing)
        k0 = min(int(nms_top_k), M) if nms_top_k > -1 else M

        def per_class(bb, sc):
            if k0 == M:
                return one_class(bb, sc)
            sv, si = jax.lax.top_k(sc, k0)
            dec = one_class(bb[si], sv)
            return jnp.zeros_like(sc).at[si].set(dec)

        decayed = jax.vmap(                     # over batch
            lambda bb, ss: jax.vmap(            # over classes
                lambda sc: per_class(bb, sc))(ss))(b, s_in)
        if C_idx >= 0:
            decayed = decayed.at[:, C_idx].set(0.0)
        decayed = jnp.where(decayed > post_threshold, decayed, 0.0)
        flat = decayed.reshape(N, C * M)
        k = min(int(keep_top_k), C * M)
        top_s, top_i = jax.lax.top_k(flat, k)
        cls = (top_i // M).astype(jnp.float32)
        box = jnp.take_along_axis(b, (top_i % M)[..., None], axis=1)
        valid = top_s > 0.0
        out = jnp.concatenate(
            [jnp.where(valid, cls, -1.0)[..., None],
             jnp.where(valid, top_s, -1.0)[..., None],
             jnp.where(valid[..., None], box, -1.0)], axis=-1)
        return out, valid.sum(-1).astype(jnp.int32)

    return apply_op("matrix_nms", fn, bboxes, scores)


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n: int):
    """Merge per-FPN-level proposals and keep the global top-n by
    score. ~ fluid collect_fpn_proposals / collect_fpn_proposals_op.cc.
    multi_rois: list of (Ri, 4); multi_scores: list of (Ri,).
    Returns (rois (n, 4), scores (n,)) with n <= post_nms_top_n."""
    if len(multi_rois) != len(multi_scores):
        raise ValueError(f"collect_fpn_proposals: {len(multi_rois)} roi "
                         f"levels vs {len(multi_scores)} score levels")
    per_r = [_arr(r).astype(np.float32).reshape(-1, 4)
             for r in multi_rois]
    per_s = [_arr(s).astype(np.float32).reshape(-1)
             for s in multi_scores]
    for i, (r, s) in enumerate(zip(per_r, per_s)):
        if len(r) != len(s):  # totals can match while levels mispair
            raise ValueError(f"collect_fpn_proposals: level {i} has "
                             f"{len(r)} rois vs {len(s)} scores")
    rois = np.concatenate(per_r)
    sc = np.concatenate(per_s)
    # stable sort: deterministic tie order at the top-n cutoff
    order = np.argsort(-sc, kind="stable")[:int(post_nms_top_n)]
    return Tensor(rois[order]), Tensor(sc[order])


def multiclass_nms(bboxes, scores, score_threshold: float = 0.0,
                   nms_top_k: int = 400, keep_top_k: int = 100,
                   nms_threshold: float = 0.3, normalized: bool = True,
                   nms_eta: float = 1.0, background_label: int = 0):
    """Per-class NMS + cross-class keep_top_k. ~ detection.py:3276 /
    multiclass_nms_op.cc — with the TPU-side contract: FIXED-size
    outputs padded to keep_top_k per image when keep_top_k >= 0.
    keep_top_k < 0 keeps everything; the padded width then becomes the
    largest per-image post-NMS count (data-dependent — host-only path).

    bboxes (N, M, 4), scores (N, C, M) ->
      out (N, K, 6) rows [label, score, x1, y1, x2, y2]
      (label -1 on padding), valid counts (N,) int32.
    """
    b = _arr(bboxes).astype(np.float32)
    s = _arr(scores).astype(np.float32)
    N, C, M = s.shape
    norm = 0.0 if normalized else 1.0

    per_image = []
    for n in range(N):
        dets = []  # (label, score, box)
        for c in range(C):
            if c == background_label:
                continue
            mask = s[n, c] > score_threshold
            if not mask.any():
                continue
            idx = np.nonzero(mask)[0]
            # nms_util.h resizes whenever top_k > -1 (0 keeps nothing)
            if nms_top_k > -1 and len(idx) > nms_top_k:
                idx = idx[np.argsort(-s[n, c, idx])[:nms_top_k]]
            for k in _greedy_nms(b[n, idx], s[n, c, idx], nms_threshold,
                                 norm, nms_eta):
                dets.append((c, s[n, c, idx[k]], b[n, idx[k]]))
        dets.sort(key=lambda d: -d[1])
        if keep_top_k > -1:
            dets = dets[:int(keep_top_k)]
        per_image.append(dets)

    # keep_top_k < 0 means keep ALL detections; 0 keeps none — the
    # reference resizes whenever keep_top_k > -1 (multiclass_nms_op.cc).
    # The unlimited case pads to the largest per-image post-NMS count.
    K = int(keep_top_k) if keep_top_k >= 0 else \
        max((len(d) for d in per_image), default=0)
    out = np.full((N, K, 6), -1.0, np.float32)
    counts = np.zeros((N,), np.int32)
    for n, dets in enumerate(per_image):
        for r, (c, sc, box) in enumerate(dets):
            out[n, r, 0] = c
            out[n, r, 1] = sc
            out[n, r, 2:] = box
        counts[n] = len(dets)
    return Tensor(out), Tensor(counts)
