"""Vision transforms (numpy, CHW float32).

~ python/paddle/vision/transforms/ — host-side preprocessing composed into
the DataLoader worker threads.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, dtype=np.float32).reshape(-1, 1, 1)

    def __call__(self, x):
        return (x - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, x):
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 2:
            x = x[None]
        elif x.ndim == 3 and x.shape[-1] in (1, 3, 4):
            x = x.transpose(2, 0, 1)
        if x.max() > 1.5:
            x = x / 255.0
        return x


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        c, h, w = x.shape
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        return x[:, ys][:, :, xs]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        c, h, w = x.shape
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return x[:, i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, x):
        if self.padding:
            x = np.pad(x, [(0, 0), (self.padding, self.padding),
                           (self.padding, self.padding)])
        c, h, w = x.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return x[:, i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return x[:, :, ::-1].copy()
        return x


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, x):
        c, h, w = x.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = x[:, i:i + ch, j:j + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(x))


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, x):
        return np.transpose(x, self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, x):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(x * alpha, 0, 1).astype(np.float32)


# ---- functional API --------------------------------------------------------
# ~ python/paddle/vision/transforms/functional.py (+functional_cv2.py):
# host-side numpy ops on CHW float arrays, composed in DataLoader workers.

def _chw(x):
    x = np.asarray(x)
    if x.ndim == 2:
        x = x[None]
    return x


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(_chw(img))


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(_chw(img), [(0, 0), (t, b), (l, r)], mode=mode, **kw)


def crop(img, top, left, height, width):
    return _chw(img)[:, top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(_chw(img))


def hflip(img):
    return _chw(img)[:, :, ::-1].copy()


def vflip(img):
    return _chw(img)[:, ::-1].copy()


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(np.asarray(img,
                                                        dtype=np.float32))


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from scipy import ndimage
    x = _chw(img)
    order = 0 if interpolation == "nearest" else 1
    out = ndimage.rotate(x, -angle, axes=(2, 1), reshape=expand, order=order,
                         mode="constant", cval=fill)
    return out.astype(x.dtype)


def to_grayscale(img, num_output_channels=1):
    x = _chw(img).astype(np.float32)
    if x.shape[0] >= 3:
        g = (0.299 * x[0] + 0.587 * x[1] + 0.114 * x[2])[None]
    else:
        g = x[:1]
    return np.repeat(g, num_output_channels, axis=0)


def adjust_brightness(img, brightness_factor):
    x = _chw(img).astype(np.float32)
    hi = 1.0 if x.max() <= 1.5 else 255.0
    return np.clip(x * brightness_factor, 0, hi)


def adjust_contrast(img, contrast_factor):
    x = _chw(img).astype(np.float32)
    hi = 1.0 if x.max() <= 1.5 else 255.0
    mean = to_grayscale(x)[0].mean()
    return np.clip(mean + contrast_factor * (x - mean), 0, hi)


def adjust_saturation(img, saturation_factor):
    x = _chw(img).astype(np.float32)
    hi = 1.0 if x.max() <= 1.5 else 255.0
    gray = to_grayscale(x, x.shape[0])
    return np.clip(gray + saturation_factor * (x - gray), 0, hi)


def _rgb_to_hsv(x):
    r, g, b = x[0], x[1], x[2]
    mx = np.max(x[:3], axis=0)
    mn = np.min(x[:3], axis=0)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b) / diff)[m] % 6
    m = mx == g
    h[m] = ((b - r) / diff + 2)[m]
    m = mx == b
    h[m] = ((r - g) / diff + 4)[m]
    h = h / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    return np.stack([h, s, mx])


def _hsv_to_rgb(hsv):
    h, s, v = hsv[0] * 6.0, hsv[1], hsv[2]
    i = np.floor(h).astype(np.int32) % 6
    f = h - np.floor(h)
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    choices = [np.stack(c) for c in
               [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
                (v, p, q)]]
    out = np.zeros_like(np.stack([v, v, v]))
    for k in range(6):
        out = np.where(i == k, choices[k], out)
    return out


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    x = _chw(img).astype(np.float32)
    scaled = x.max() > 1.5
    y = x / 255.0 if scaled else x
    hsv = _rgb_to_hsv(y)
    hsv[0] = (hsv[0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv)
    return (out * 255.0 if scaled else out).astype(x.dtype)


def erase(img, i, j, h, w, v, inplace=False):
    x = _chw(img) if inplace else _chw(img).copy()
    x[:, i:i + h, j:j + w] = v
    return x


# ---- transform classes -----------------------------------------------------

class BaseTransform:
    """~ python/paddle/vision/transforms/transforms.py BaseTransform: keyed
    multi-input transforms; subclasses implement _apply_image (and
    optionally _apply_{coords,boxes,mask})."""

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)
        self.params = None

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            self.params = self._get_params(inputs)
            out = []
            for key, data in zip(self.keys, inputs):
                apply = getattr(self, f"_apply_{key}", None)
                out.append(apply(data) if apply else data)
            return tuple(out)
        self.params = self._get_params((inputs,))
        return self._apply_image(inputs)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _chw(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = min(value, 0.5)

    def _apply_image(self, img):
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """~ transforms.ColorJitter: random brightness/contrast/saturation/hue
    in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class RandomErasing(BaseTransform):
    """~ transforms.RandomErasing (cutout regularization)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        x = _chw(img)
        if np.random.rand() >= self.prob:
            return x
        c, h, w = x.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                v = np.random.standard_normal((c, eh, ew)).astype(x.dtype) \
                    if self.value == "random" else self.value
                return erase(x, i, j, eh, ew, v, self.inplace)
        return x
