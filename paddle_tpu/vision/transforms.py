"""Vision transforms (numpy, CHW float32).

~ python/paddle/vision/transforms/ — host-side preprocessing composed into
the DataLoader worker threads.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, dtype=np.float32).reshape(-1, 1, 1)

    def __call__(self, x):
        return (x - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, x):
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 2:
            x = x[None]
        elif x.ndim == 3 and x.shape[-1] in (1, 3, 4):
            x = x.transpose(2, 0, 1)
        if x.max() > 1.5:
            x = x / 255.0
        return x


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        c, h, w = x.shape
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        return x[:, ys][:, :, xs]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        c, h, w = x.shape
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return x[:, i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, x):
        if self.padding:
            x = np.pad(x, [(0, 0), (self.padding, self.padding),
                           (self.padding, self.padding)])
        c, h, w = x.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return x[:, i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return x[:, :, ::-1].copy()
        return x


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, x):
        c, h, w = x.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = x[:, i:i + ch, j:j + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(x))


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, x):
        return np.transpose(x, self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, x):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(x * alpha, 0, 1).astype(np.float32)
