"""jit-compiled detection geometry — the TPU training path.

The host module (``vision/detection.py``) keeps the reference's eager
semantics for host-side post-processing (greedy NMS and friends, which
the reference itself pins to CPU). THIS module provides pure-jnp,
fixed-shape twins of the geometry and training-path ops — the ones the
reference ships as CUDA kernels (prior_box_op.cu, anchor_generator_op.cu,
box_coder_op.cu, box_clip_op.cu, iou_similarity_op.cu,
generate_proposals_op.cu, distribute_fpn_proposals_op.cu,
collect_fpn_proposals_op.cu, target_assign_op.h, the MultiBoxLoss
recipe) — so an SSD/RCNN train step compiles end-to-end under jax.jit.

XLA static-shape contract: ground truth arrives padded to a fixed G_max
with a boolean validity mask; every output is fixed-size with
counts/masks instead of the reference's LoD variable-length tensors.
Anchor/prior grids take only static hyperparameters, so inside a jitted
step they constant-fold into the executable.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "iou_matrix", "clip_boxes", "encode_center_size",
    "decode_center_size", "anchor_grid", "prior_box_grid",
    "density_prior_box_grid", "match_priors", "ssd_loss_jit",
    "generate_proposals_jit", "distribute_fpn_proposals_jit",
    "collect_fpn_proposals_jit",
]

NEG_INF = -1e30


# --- pairwise geometry ----------------------------------------------------

def iou_matrix(a, b, normalized: bool = True):
    """(N, 4) x (M, 4) -> (N, M) IoU. ~ iou_similarity_op.h (the +1
    boundary-pixel convention when unnormalized)."""
    norm = 0.0 if normalized else 1.0
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    inter_w = jnp.clip(jnp.minimum(ax2[:, None], bx2[None, :])
                       - jnp.maximum(ax1[:, None], bx1[None, :]) + norm,
                       0.0, None)
    inter_h = jnp.clip(jnp.minimum(ay2[:, None], by2[None, :])
                       - jnp.maximum(ay1[:, None], by1[None, :]) + norm,
                       0.0, None)
    inter = inter_w * inter_h
    area_a = (ax2 - ax1 + norm) * (ay2 - ay1 + norm)
    area_b = (bx2 - bx1 + norm) * (by2 - by1 + norm)
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


def clip_boxes(boxes, im_info):
    """Clip (..., 4) boxes to the ORIGINAL image extent. ~ box_clip_op.h:
    im_info = (H, W, scale) of the network input; boxes clip to
    [0, round(W/scale)-1] x [0, round(H/scale)-1]."""
    info = im_info.reshape(-1).astype(jnp.float32)
    scale = jnp.where(info[2] > 0, info[2], 1.0) if info.shape[0] > 2 \
        else jnp.float32(1.0)
    hmax = jnp.round(info[0] / scale) - 1.0
    wmax = jnp.round(info[1] / scale) - 1.0
    x = jnp.clip(boxes[..., 0::2], 0.0, wmax)
    y = jnp.clip(boxes[..., 1::2], 0.0, hmax)
    out = jnp.stack([x[..., 0], y[..., 0], x[..., 1], y[..., 1]], -1)
    return out.astype(boxes.dtype)


# --- box coding (box_coder_op.cc semantics) -------------------------------

def encode_center_size(priors, prior_var, targets, normalized: bool = True):
    """targets (G, 4) corners vs priors (P, 4) -> (G, P, 4) offsets."""
    norm = 0.0 if normalized else 1.0
    p = priors.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    pw = p[:, 2] - p[:, 0] + norm
    ph = p[:, 3] - p[:, 1] + norm
    pcx = p[:, 0] + pw * 0.5
    pcy = p[:, 1] + ph * 0.5
    tw = t[:, 2] - t[:, 0] + norm
    th = t[:, 3] - t[:, 1] + norm
    tcx = t[:, 0] + tw * 0.5
    tcy = t[:, 1] + th * 0.5
    ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
    oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
    ow = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
    oh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
    out = jnp.stack([ox, oy, ow, oh], -1)
    if prior_var is not None:
        pv = jnp.broadcast_to(jnp.asarray(prior_var, jnp.float32),
                              p.shape)
        out = out / pv[None, :, :]
    return out


def decode_center_size(priors, prior_var, deltas, axis: int = 0,
                       normalized: bool = True):
    """deltas (N, M, 4) + priors (broadcast over axis 0 or 1) -> corners.
    A 2-D deltas array decodes elementwise against its own prior row
    (the RPN one-delta-per-anchor case)."""
    norm = 0.0 if normalized else 1.0
    p = priors.astype(jnp.float32)
    d = deltas.astype(jnp.float32)
    pw = p[:, 2] - p[:, 0] + norm
    ph = p[:, 3] - p[:, 1] + norm
    pcx = p[:, 0] + pw * 0.5
    pcy = p[:, 1] + ph * 0.5
    pv = None if prior_var is None else jnp.broadcast_to(
        jnp.asarray(prior_var, jnp.float32), p.shape)
    if d.ndim == 2:  # one delta per prior, elementwise
        pass
    elif axis == 0:
        pw, ph, pcx, pcy = (a[None, :] for a in (pw, ph, pcx, pcy))
        pv = None if pv is None else pv[None, :, :]
    else:
        pw, ph, pcx, pcy = (a[:, None] for a in (pw, ph, pcx, pcy))
        pv = None if pv is None else pv[:, None, :]
    if pv is not None:
        d = d * pv
    cx = d[..., 0] * pw + pcx
    cy = d[..., 1] * ph + pcy
    w = jnp.exp(d[..., 2]) * pw
    h = jnp.exp(d[..., 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)


# --- static prior/anchor grids (constant-fold under jit) ------------------

def anchor_grid(H: int, W: int, anchor_sizes: Sequence[float],
                aspect_ratios: Sequence[float],
                stride: Sequence[float] = (16.0, 16.0),
                offset: float = 0.5):
    """(H, W, A, 4) RPN anchors, reference Detectron convention
    (anchor_generator_op.h: rounded base w/h at stride scale,
    ratio-outer/size-inner, offset*(stride-1) centers, +/-0.5*(w-1)
    corners). All-static args: a compile-time constant under jit."""
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for ar in aspect_ratios:
        base_w = round(math.sqrt(sw * sh / ar))
        base_h = round(base_w * ar)
        for s in anchor_sizes:
            whs.append((float(s) / sw * base_w, float(s) / sh * base_h))
    wh = jnp.asarray(whs, jnp.float32)                      # (A, 2)
    cx = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
    cy = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
    cxg = jnp.broadcast_to(cx[None, :], (H, W))
    cyg = jnp.broadcast_to(cy[:, None], (H, W))
    return jnp.stack([
        cxg[:, :, None] - (wh[None, None, :, 0] - 1) / 2,
        cyg[:, :, None] - (wh[None, None, :, 1] - 1) / 2,
        cxg[:, :, None] + (wh[None, None, :, 0] - 1) / 2,
        cyg[:, :, None] + (wh[None, None, :, 1] - 1) / 2,
    ], -1)


def _cell_centers(H, W, step_w, step_h, offset):
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    return (jnp.broadcast_to(cx[None, :], (H, W)),
            jnp.broadcast_to(cy[:, None], (H, W)))


def prior_box_grid(H: int, W: int, image_h: int, image_w: int,
                   min_sizes: Sequence[float],
                   max_sizes: Optional[Sequence[float]] = None,
                   aspect_ratios: Sequence[float] = (1.0,),
                   flip: bool = False, clip: bool = False,
                   steps: Sequence[float] = (0.0, 0.0),
                   offset: float = 0.5,
                   min_max_aspect_ratios_order: bool = False):
    """(H, W, P, 4) normalized SSD priors. ~ prior_box_op.cc (same
    enumeration as the host twin vision/detection.py::prior_box)."""
    ih, iw = float(image_h), float(image_w)
    step_h = steps[1] if steps[1] > 0 else ih / H
    step_w = steps[0] if steps[0] > 0 else iw / W
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs: List = []
    for i, ms in enumerate(float(m) for m in min_sizes):
        sq = (math.sqrt(ms * float(max_sizes[i])),) * 2 if max_sizes \
            else None
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if sq:
                whs.append(sq)
            whs.extend((ms * math.sqrt(ar), ms / math.sqrt(ar))
                       for ar in ars if abs(ar - 1.0) >= 1e-6)
        else:
            whs.extend((ms * math.sqrt(ar), ms / math.sqrt(ar))
                       for ar in ars)
            if sq:
                whs.append(sq)
    wh = jnp.asarray(whs, jnp.float32)
    cxg, cyg = _cell_centers(H, W, step_w, step_h, offset)
    boxes = jnp.stack([
        (cxg[:, :, None] - wh[None, None, :, 0] / 2) / iw,
        (cyg[:, :, None] - wh[None, None, :, 1] / 2) / ih,
        (cxg[:, :, None] + wh[None, None, :, 0] / 2) / iw,
        (cyg[:, :, None] + wh[None, None, :, 1] / 2) / ih,
    ], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def density_prior_box_grid(H: int, W: int, image_h: int, image_w: int,
                           densities: Sequence[int],
                           fixed_sizes: Sequence[float],
                           fixed_ratios: Sequence[float] = (1.0,),
                           steps: Sequence[float] = (0.0, 0.0),
                           offset: float = 0.5):
    """(H, W, P, 4) density priors. ~ density_prior_box_op.cu (integer
    averaged-step sub-grid shifts; corners always clamped to [0, 1])."""
    if len(densities) != len(fixed_sizes):
        raise ValueError("densities and fixed_sizes must pair up 1:1")
    ih, iw = float(image_h), float(image_w)
    step_h = steps[1] if steps[1] > 0 else ih / H
    step_w = steps[0] if steps[0] > 0 else iw / W
    step_avg = int(0.5 * (step_w + step_h))
    entries = []
    for dens, fs in zip(densities, fixed_sizes):
        dens = int(dens)
        shift = int(step_avg / dens)
        for r in fixed_ratios:
            bw, bh = fs * math.sqrt(r), fs / math.sqrt(r)
            for di in range(dens):
                for dj in range(dens):
                    entries.append(((dj + 0.5) * shift - step_avg / 2.0,
                                    (di + 0.5) * shift - step_avg / 2.0,
                                    bw, bh))
    e = jnp.asarray(entries, jnp.float32)
    cxg, cyg = _cell_centers(H, W, step_w, step_h, offset)
    ctrx = cxg[:, :, None] + e[None, None, :, 0]
    ctry = cyg[:, :, None] + e[None, None, :, 1]
    boxes = jnp.stack([
        (ctrx - e[None, None, :, 2] / 2) / iw,
        (ctry - e[None, None, :, 3] / 2) / ih,
        (ctrx + e[None, None, :, 2] / 2) / iw,
        (ctry + e[None, None, :, 3] / 2) / ih,
    ], -1)
    return jnp.clip(boxes, 0.0, 1.0)


# --- matching + the SSD multibox loss, fully traced -----------------------

def match_priors(iou, gt_mask=None, match_type: str = "per_prediction",
                 dist_threshold: float = 0.5):
    """Greedy bipartite matching under jit. ~ bipartite_match_op.cc.

    iou (G, P) similarity; gt_mask (G,) marks real (non-padding) rows.
    Returns (match_idx (P,) int32 gt-per-prior or -1, match_dist (P,)).
    The greedy loop runs a fixed G iterations (lax.fori_loop); retired
    rows/columns and sub-zero maxima are handled by masking, matching
    the host twin's early-break semantics exactly.
    """
    G, P = iou.shape
    d = iou.astype(jnp.float32)
    if gt_mask is not None:
        d = jnp.where(gt_mask[:, None], d, 0.0)

    def body(_, state):
        work, midx, mdist = state
        flat = jnp.argmax(work)
        g, p = flat // P, flat % P
        take = work[g, p] > 0.0
        midx = midx.at[p].set(jnp.where(take, g.astype(jnp.int32),
                                        midx[p]))
        mdist = mdist.at[p].set(jnp.where(take, d[g, p], mdist[p]))
        row_gone = jnp.where(take & (jnp.arange(G) == g), NEG_INF, 0.0)
        col_gone = jnp.where(take & (jnp.arange(P) == p), NEG_INF, 0.0)
        work = work + row_gone[:, None] + col_gone[None, :]
        return work, midx, mdist

    midx0 = jnp.full((P,), -1, jnp.int32)
    mdist0 = jnp.zeros((P,), jnp.float32)
    _, midx, mdist = jax.lax.fori_loop(0, min(G, P), body,
                                       (d, midx0, mdist0))
    if match_type == "per_prediction":
        best_gt = jnp.argmax(d, axis=0).astype(jnp.int32)
        best_dist = jnp.max(d, axis=0)
        extra = (midx < 0) & (best_dist > dist_threshold)
        midx = jnp.where(extra, best_gt, midx)
        mdist = jnp.where(extra, best_dist, mdist)
    return midx, mdist


def ssd_loss_jit(location, confidence, gt_boxes, gt_labels, gt_mask,
                 prior_box, prior_box_var=None, background_label: int = 0,
                 overlap_threshold: float = 0.5,
                 neg_pos_ratio: float = 3.0, loc_loss_weight: float = 1.0,
                 conf_loss_weight: float = 1.0):
    """The SSD multibox loss for ONE image, fully inside jit.
    ~ the MultiBoxLoss recipe (fluid layers/detection.py:1527):
    per_prediction matching, smooth-L1 on encoded offsets, softmax CE
    with rank-exact 3:1 hard negative mining (a sorted-rank mask, so the
    dynamic keep count needs no dynamic-shape top_k).

    location (P, 4), confidence (P, C) logits; gt_boxes (G, 4) padded,
    gt_labels (G,) int, gt_mask (G,) bool marks real rows;
    prior_box (P, 4). Returns a scalar. vmap over images for a batch.
    """
    P = prior_box.shape[0]
    iou = iou_matrix(gt_boxes, prior_box)
    midx, _ = match_priors(iou, gt_mask, "per_prediction",
                           overlap_threshold)
    enc = encode_center_size(prior_box, prior_box_var, gt_boxes)  # (G,P,4)
    matched = midx >= 0
    safe = jnp.clip(midx, 0, None)
    loc_t = jnp.where(matched[:, None],
                      enc[safe, jnp.arange(P)], 0.0)
    conf_t = jnp.where(matched, gt_labels.astype(jnp.int32)[safe],
                       background_label)
    n_pos = jnp.maximum(jnp.sum(matched), 1)
    n_neg_keep = jnp.minimum((neg_pos_ratio * n_pos).astype(jnp.int32),
                             P - n_pos)

    logp = jax.nn.log_softmax(confidence.astype(jnp.float32), -1)
    ce = -jnp.take_along_axis(logp, conf_t[:, None], -1)[:, 0]
    # rank-based hard-negative mining: EXACTLY the top-n_neg_keep
    # background CEs (ties broken by sort order, as the host twin's
    # top_k does) — rank masks make the dynamic count jit-safe
    neg_ce = jnp.where(matched, -jnp.inf, ce)
    order = jnp.argsort(-neg_ce)
    keep_sorted = jnp.arange(P) < n_neg_keep
    neg_keep = jnp.zeros((P,), bool).at[order].set(keep_sorted)
    conf_loss = jnp.sum(jnp.where(matched | neg_keep, ce, 0.0))
    diff = jnp.abs((location - loc_t).astype(jnp.float32))
    sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    loc_loss = jnp.sum(jnp.where(matched[:, None], sl1, 0.0))
    return (conf_loss_weight * conf_loss
            + loc_loss_weight * loc_loss) / n_pos


# --- RPN proposals + FPN routing, fully traced ----------------------------

def _nms_fixed(boxes, valid, nms_thresh: float, max_keep: int,
               eta: float = 1.0):
    """Greedy NMS over score-DESCENDING ``boxes`` with a fixed pick
    count. Returns (keep_idx (max_keep,) int32 padded -1, count).
    The reference's +1 pixel convention (norm=1), like
    generate_proposals_op.cc's NMS."""
    n = boxes.shape[0]
    areas = ((boxes[:, 2] - boxes[:, 0] + 1.0)
             * (boxes[:, 3] - boxes[:, 1] + 1.0))

    def body(i, state):
        alive, keep, th = state
        any_alive = jnp.any(alive)
        # boxes are score-sorted: the next pick is the first alive row
        p = jnp.argmax(alive)  # first True (argmax of bool)
        keep = keep.at[i].set(jnp.where(any_alive, p.astype(jnp.int32),
                                        -1))
        x1 = jnp.maximum(boxes[p, 0], boxes[:, 0])
        y1 = jnp.maximum(boxes[p, 1], boxes[:, 1])
        x2 = jnp.minimum(boxes[p, 2], boxes[:, 2])
        y2 = jnp.minimum(boxes[p, 3], boxes[:, 3])
        inter = (jnp.clip(x2 - x1 + 1.0, 0, None)
                 * jnp.clip(y2 - y1 + 1.0, 0, None))
        iou = inter / (areas[p] + areas - inter + 1e-10)
        suppress = iou > th
        alive = jnp.where(any_alive, alive & ~suppress, alive)
        th = jnp.where((eta < 1.0) & (th > 0.5), th * eta, th)
        return alive, keep, th

    keep0 = jnp.full((max_keep,), -1, jnp.int32)
    _, keep, _ = jax.lax.fori_loop(
        0, max_keep, body, (valid, keep0, jnp.float32(nms_thresh)))
    return keep, jnp.sum(keep >= 0)


def generate_proposals_jit(scores, bbox_deltas, im_info, anchors,
                           variances, pre_nms_top_n: int = 6000,
                           post_nms_top_n: int = 1000,
                           nms_thresh: float = 0.5,
                           min_size: float = 0.1, eta: float = 1.0):
    """RPN proposals for ONE image, fully inside jit.
    ~ generate_proposals_op.cc (the reference's CUDA path). scores
    (A, H, W); bbox_deltas (4A, H, W); im_info (3,); anchors/variances
    (H, W, A, 4) or flat (H*W*A, 4). Returns (rois (post_nms_top_n, 4)
    zero-padded, scores (post_nms_top_n,), count). vmap over images.
    """
    A, H, W = scores.shape
    s = scores.transpose(1, 2, 0).reshape(-1)
    d = bbox_deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
    an = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4)
    dec = decode_center_size(an, None, d * var)   # per-anchor elementwise
    info = im_info.reshape(-1).astype(jnp.float32)
    hmax, wmax = info[0] - 1.0, info[1] - 1.0
    x = jnp.clip(dec[:, 0::2], 0.0, wmax)
    y = jnp.clip(dec[:, 1::2], 0.0, hmax)
    dec = jnp.stack([x[:, 0], y[:, 0], x[:, 1], y[:, 1]], -1)
    ms = max(min_size, 1.0) * jnp.where(info[2] > 0, info[2], 1.0)
    wh = dec[:, 2:] - dec[:, :2] + 1.0
    valid = jnp.all(wh >= ms, axis=1)

    k = min(int(pre_nms_top_n), s.shape[0])
    sv, si = jax.lax.top_k(jnp.where(valid, s, -jnp.inf), k)
    boxes = dec[si]
    keep, count = _nms_fixed(boxes, sv > -jnp.inf, nms_thresh,
                             int(post_nms_top_n), eta)
    picked = keep >= 0
    safe = jnp.clip(keep, 0, None)
    rois = jnp.where(picked[:, None], boxes[safe], 0.0)
    rsc = jnp.where(picked, sv[safe], 0.0)
    return rois, rsc, count


def distribute_fpn_proposals_jit(rois, valid, min_level: int,
                                 max_level: int, refer_level: int,
                                 refer_scale: float):
    """Route (R, 4) rois to FPN levels, fixed shapes.
    ~ distribute_fpn_proposals_op.cu: level = clamp(floor(refer_level +
    log2(sqrt(area)/refer_scale))). Returns (per-level rois
    (L, R, 4) compacted to the front, per-level counts (L,),
    restore_row (R,) — the row index of each input roi in the
    concatenated (L*R, 4) layout, -1 for invalid inputs)."""
    r = rois.reshape(-1, 4).astype(jnp.float32)
    R = r.shape[0]
    w = jnp.clip(r[:, 2] - r[:, 0], 0.0, None)
    h = jnp.clip(r[:, 3] - r[:, 1], 0.0, None)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(refer_level + jnp.log2(
        jnp.maximum(scale, 1e-6) / refer_scale))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl = jnp.where(valid, lvl, -1)

    outs, counts, restore = [], [], jnp.full((R,), -1, jnp.int32)
    for i, level in enumerate(range(min_level, max_level + 1)):
        m = lvl == level
        # stable compaction: rows of this level move to the front in
        # input order, the rest pad the tail
        order = jnp.argsort(jnp.where(m, 0, 1), stable=True)
        outs.append(jnp.where((jnp.arange(R) < jnp.sum(m))[:, None],
                              r[order], 0.0))
        counts.append(jnp.sum(m))
        rank = jnp.cumsum(m) - 1
        restore = jnp.where(m, i * R + rank.astype(jnp.int32), restore)
    return jnp.stack(outs), jnp.stack(counts), restore


def collect_fpn_proposals_jit(multi_rois, multi_scores, multi_valid,
                              post_nms_top_n: int
                              ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                         jnp.ndarray]:
    """Merge per-level proposals, keep the global top-n by score.
    ~ collect_fpn_proposals_op.cc. multi_rois (L, R, 4) or list;
    multi_scores (L, R); multi_valid (L, R) bool. Returns
    (rois (n, 4), scores (n,), count) with n = post_nms_top_n fixed."""
    rois = jnp.concatenate([r.reshape(-1, 4) for r in multi_rois])
    sc = jnp.concatenate([s.reshape(-1) for s in multi_scores])
    vd = jnp.concatenate([v.reshape(-1) for v in multi_valid])
    masked = jnp.where(vd, sc, -jnp.inf)
    k = min(int(post_nms_top_n), masked.shape[0])
    sv, si = jax.lax.top_k(masked, k)
    picked = sv > -jnp.inf
    out_r = jnp.where(picked[:, None], rois[si], 0.0)
    out_s = jnp.where(picked, sv, 0.0)
    return out_r, out_s, jnp.sum(picked)
