"""MobileNetV3, GoogLeNet, InceptionV3 + variant factories.

~ python/paddle/vision/models/{mobilenetv3,googlenet,inceptionv3}.py and the
resnext/wide/densenet/shufflenet variant constructors of the reference's
model zoo. Plain conv/SE compositions — XLA fuses the conv+BN+act chains.
"""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU(), "hardswish": nn.Hardswish(),
                    None: None}[act]

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, reduce=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, c // reduce, 1)
        self.fc2 = nn.Conv2D(c // reduce, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(_ConvBNAct(in_c, exp_c, 1, act=act))
        layers.append(_ConvBNAct(exp_c, exp_c, k, stride=stride,
                                 padding=k // 2, groups=exp_c, act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp_c))
        layers.append(_ConvBNAct(exp_c, out_c, 1, act=None))
        self.body = nn.Sequential(*layers)

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, exp, out, se, act, stride
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]

_V3_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    """~ vision/models/mobilenetv3.py MobileNetV3Small/Large."""

    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()

        def c(v):
            return max(8, int(v * scale + 4) // 8 * 8)

        self.stem = _ConvBNAct(3, c(16), 3, stride=2, padding=1,
                               act="hardswish")
        blocks = []
        in_c = c(16)
        for k, exp, out, se, act, stride in config:
            blocks.append(_InvertedResidualV3(in_c, c(exp), c(out), k,
                                              stride, se, act))
            in_c = c(out)
        last_conv = c(config[-1][1])
        blocks.append(_ConvBNAct(in_c, last_conv, 1, act="hardswish"))
        self.blocks = nn.Sequential(*blocks)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


# ---- GoogLeNet (Inception v1) ----------------------------------------------

class _InceptionBlock(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, c1, 1)
        self.b2 = nn.Sequential(_ConvBNAct(in_c, c3r, 1),
                                _ConvBNAct(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_ConvBNAct(in_c, c5r, 1),
                                _ConvBNAct(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                _ConvBNAct(in_c, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    """~ vision/models/googlenet.py — returns (main, aux1, aux2) logits in
    train mode like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.stem = nn.Sequential(
            _ConvBNAct(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, 2, padding=1),
            _ConvBNAct(64, 64, 1),
            _ConvBNAct(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _InceptionBlock(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _InceptionBlock(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _InceptionBlock(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _InceptionBlock(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _InceptionBlock(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _InceptionBlock(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _InceptionBlock(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _InceptionBlock(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _InceptionBlock(832, 384, 192, 384, 48, 128, 128)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)
        # aux heads
        self.aux1 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                  _ConvBNAct(512, 128, 1))
        self.aux1_fc = nn.Sequential(nn.Linear(128 * 16, 1024), nn.ReLU(),
                                     nn.Dropout(0.7),
                                     nn.Linear(1024, num_classes))
        self.aux2 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                  _ConvBNAct(528, 128, 1))
        self.aux2_fc = nn.Sequential(nn.Linear(128 * 16, 1024), nn.ReLU(),
                                     nn.Dropout(0.7),
                                     nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1_fc(flatten(self.aux1(x), 1)) if self.training \
            else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2_fc(flatten(self.aux2(x), 1)) if self.training \
            else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        out = self.fc(self.dropout(flatten(self.pool(x), 1)))
        if self.training:
            return out, aux2, aux1
        return out


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---- InceptionV3 -----------------------------------------------------------

class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, 64, 1)
        self.b2 = nn.Sequential(_ConvBNAct(in_c, 48, 1),
                                _ConvBNAct(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBNAct(in_c, 64, 1),
                                _ConvBNAct(64, 96, 3, padding=1),
                                _ConvBNAct(96, 96, 3, padding=1))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBNAct(in_c, pool_c, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], 1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, 384, 3, stride=2)
        self.b2 = nn.Sequential(_ConvBNAct(in_c, 64, 1),
                                _ConvBNAct(64, 96, 3, padding=1),
                                _ConvBNAct(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, 192, 1)
        self.b2 = nn.Sequential(
            _ConvBNAct(in_c, c7, 1),
            _ConvBNAct(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBNAct(c7, 192, (7, 1), padding=(3, 0)))
        self.b3 = nn.Sequential(
            _ConvBNAct(in_c, c7, 1),
            _ConvBNAct(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBNAct(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBNAct(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBNAct(c7, 192, (1, 7), padding=(0, 3)))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBNAct(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], 1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = nn.Sequential(_ConvBNAct(in_c, 192, 1),
                                _ConvBNAct(192, 320, 3, stride=2))
        self.b2 = nn.Sequential(
            _ConvBNAct(in_c, 192, 1),
            _ConvBNAct(192, 192, (1, 7), padding=(0, 3)),
            _ConvBNAct(192, 192, (7, 1), padding=(3, 0)),
            _ConvBNAct(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.pool(x)], 1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, 320, 1)
        self.b2_stem = _ConvBNAct(in_c, 384, 1)
        self.b2_a = _ConvBNAct(384, 384, (1, 3), padding=(0, 1))
        self.b2_b = _ConvBNAct(384, 384, (3, 1), padding=(1, 0))
        self.b3_stem = nn.Sequential(_ConvBNAct(in_c, 448, 1),
                                     _ConvBNAct(448, 384, 3, padding=1))
        self.b3_a = _ConvBNAct(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBNAct(384, 384, (3, 1), padding=(1, 0))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBNAct(in_c, 192, 1))

    def forward(self, x):
        h2 = self.b2_stem(x)
        h3 = self.b3_stem(x)
        return concat([self.b1(x),
                       concat([self.b2_a(h2), self.b2_b(h2)], 1),
                       concat([self.b3_a(h3), self.b3_b(h3)], 1),
                       self.b4(x)], 1)


class InceptionV3(nn.Layer):
    """~ vision/models/inceptionv3.py (299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.stem = nn.Sequential(
            _ConvBNAct(3, 32, 3, stride=2),
            _ConvBNAct(32, 32, 3),
            _ConvBNAct(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2),
            _ConvBNAct(64, 80, 1),
            _ConvBNAct(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
