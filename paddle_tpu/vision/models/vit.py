"""Vision Transformer (ViT) family.

The reference repo's paddle.vision zoo stops at CNNs; ViT lives in the
PaddleClas ecosystem (ppcls/arch/backbone/model_zoo/vision_transformer.py)
that BASELINE.md's config ladder draws from. Implemented here TPU-first:
patchify is a single Conv2D (one big MXU matmul per image), the encoder
is pre-LN transformer blocks whose matmuls dominate FLOPs, and the whole
forward is shape-static so one jit covers train and eval.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...core.tensor import Tensor


class PatchEmbed(nn.Layer):
    """Image -> (B, N, D) patch tokens via a stride=patch conv."""

    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 embed_dim=768):
        super().__init__()
        assert img_size % patch_size == 0
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_chans, embed_dim, kernel_size=patch_size,
                              stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                      # B, D, H/p, W/p
        B, D = x.shape[0], x.shape[1]
        x = x.reshape([B, D, -1])             # B, D, N
        return x.transpose([0, 2, 1])         # B, N, D


class MLP(nn.Layer):
    def __init__(self, dim, hidden, drop=0.0):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(hidden, dim)
        self.drop = nn.Dropout(drop)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class Block(nn.Layer):
    """Pre-LN encoder block (LN -> MHA -> +res, LN -> MLP -> +res)."""

    def __init__(self, dim, num_heads, mlp_ratio=4.0, drop=0.0,
                 attn_drop=0.0, epsilon=1e-6):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, epsilon=epsilon)
        self.attn = nn.MultiHeadAttention(dim, num_heads, dropout=attn_drop)
        self.norm2 = nn.LayerNorm(dim, epsilon=epsilon)
        self.mlp = MLP(dim, int(dim * mlp_ratio), drop=drop)

    def forward(self, x):
        y = self.norm1(x)
        x = x + self.attn(y, y, y)
        return x + self.mlp(self.norm2(x))


class VisionTransformer(nn.Layer):
    """ViT backbone + classification head.

    ~ PaddleClas vision_transformer.py (class token + learned position
    embedding + pre-LN encoder); TPU notes: all sequence ops are static
    (N = num_patches + 1 fixed at build), so XLA tiles every matmul on
    the MXU with no dynamic shapes.
    """

    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 class_num=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, drop_rate=0.0, attn_drop_rate=0.0,
                 epsilon=1e-6):
        super().__init__()
        self.class_num = class_num
        self.embed_dim = embed_dim
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter(
            [1, 1, embed_dim], default_initializer=nn.initializer.Constant(0.0))
        self.pos_embed = self.create_parameter(
            [1, n + 1, embed_dim],
            default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.pos_drop = nn.Dropout(drop_rate)
        self.blocks = nn.LayerList([
            Block(embed_dim, num_heads, mlp_ratio, drop_rate,
                  attn_drop_rate, epsilon) for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.head = (nn.Linear(embed_dim, class_num)
                     if class_num > 0 else None)

    def forward_features(self, x):
        B = x.shape[0]
        x = self.patch_embed(x)
        from ...ops.manipulation import concat
        cls = self.cls_token.expand([B, 1, self.embed_dim])
        x = concat([cls, x], axis=1) + self.pos_embed
        x = self.pos_drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.norm(x)[:, 0]

    def forward(self, x):
        x = self.forward_features(x)
        if self.head is not None:
            x = self.head(x)
        return x


def _vit(arch, **kwargs):
    cfgs = {
        "tiny": dict(embed_dim=192, depth=12, num_heads=3),
        "small": dict(embed_dim=384, depth=12, num_heads=6),
        "base": dict(embed_dim=768, depth=12, num_heads=12),
        "large": dict(embed_dim=1024, depth=24, num_heads=16),
    }
    cfg = dict(cfgs[arch])
    cfg.update(kwargs)
    return VisionTransformer(**cfg)


def vit_tiny_patch16_224(**kwargs):
    return _vit("tiny", patch_size=16, **kwargs)


def vit_small_patch16_224(**kwargs):
    return _vit("small", patch_size=16, **kwargs)


def vit_base_patch16_224(**kwargs):
    return _vit("base", patch_size=16, **kwargs)


def vit_base_patch32_224(**kwargs):
    return _vit("base", patch_size=32, **kwargs)


def vit_large_patch16_224(**kwargs):
    return _vit("large", patch_size=16, **kwargs)
