"""ConvNeXt family (A ConvNet for the 2020s).

PaddleClas-era modern CNN (ppcls/arch/backbone/model_zoo/convnext.py);
the reference repo's own zoo predates it. TPU notes: the depthwise 7x7
is a grouped conv XLA lowers well at NHWC-equivalent tilings; the
inverted-bottleneck MLP (1x1 convs as Linear over channels-last) puts
~90% of the FLOPs in plain MXU matmuls; LayerNorm is channels-last so
no transposes survive fusion.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...core.tensor import Tensor


class _LayerNormChannelsFirst(nn.Layer):
    """LayerNorm over C for (B, C, H, W) without leaving NCHW."""

    def __init__(self, dim, epsilon=1e-6):
        super().__init__()
        self.weight = self.create_parameter(
            [dim], default_initializer=nn.initializer.Constant(1.0))
        self.bias = self.create_parameter([dim], is_bias=True)
        self.eps = epsilon

    def forward(self, x):
        import jax.numpy as jnp
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        mu = jnp.mean(xv, axis=1, keepdims=True)
        var = jnp.var(xv, axis=1, keepdims=True)
        y = (xv - mu) / jnp.sqrt(var + self.eps)
        y = (y * self.weight._value[None, :, None, None]
             + self.bias._value[None, :, None, None])
        return Tensor(y.astype(xv.dtype))


class ConvNeXtBlock(nn.Layer):
    """dwconv7x7 -> LN -> pwconv(4x) -> GELU -> pwconv -> layer-scale ->
    +residual. Pointwise convs are Linear over a channels-last view."""

    def __init__(self, dim, layer_scale_init=1e-6):
        super().__init__()
        self.dwconv = nn.Conv2D(dim, dim, 7, padding=3, groups=dim)
        self.norm = nn.LayerNorm(dim, epsilon=1e-6)
        self.pwconv1 = nn.Linear(dim, 4 * dim)
        self.act = nn.GELU()
        self.pwconv2 = nn.Linear(4 * dim, dim)
        self.gamma = self.create_parameter(
            [dim],
            default_initializer=nn.initializer.Constant(layer_scale_init))

    def forward(self, x):
        inp = x
        x = self.dwconv(x)
        x = x.transpose([0, 2, 3, 1])        # channels-last for LN+MLP
        x = self.norm(x)
        x = self.pwconv2(self.act(self.pwconv1(x)))
        x = self.gamma * x
        return inp + x.transpose([0, 3, 1, 2])


class ConvNeXt(nn.Layer):
    def __init__(self, in_chans=3, class_num=1000,
                 depths=(3, 3, 9, 3), dims=(96, 192, 384, 768),
                 layer_scale_init=1e-6):
        super().__init__()
        self.downsample_layers = nn.LayerList()
        stem = nn.Sequential(
            nn.Conv2D(in_chans, dims[0], 4, stride=4),
            _LayerNormChannelsFirst(dims[0]))
        self.downsample_layers.append(stem)
        for i in range(3):
            self.downsample_layers.append(nn.Sequential(
                _LayerNormChannelsFirst(dims[i]),
                nn.Conv2D(dims[i], dims[i + 1], 2, stride=2)))
        self.stages = nn.LayerList([
            nn.Sequential(*[ConvNeXtBlock(dims[i], layer_scale_init)
                            for _ in range(depths[i])])
            for i in range(4)])
        self.norm = nn.LayerNorm(dims[-1], epsilon=1e-6)
        self.head = nn.Linear(dims[-1], class_num)

    def forward(self, x):
        for down, stage in zip(self.downsample_layers, self.stages):
            x = stage(down(x))
        x = x.mean(axis=[2, 3])              # global average pool
        return self.head(self.norm(x))


def _convnext(depths, dims, **kwargs):
    return ConvNeXt(depths=depths, dims=dims, **kwargs)


def convnext_tiny(**kwargs):
    return _convnext((3, 3, 9, 3), (96, 192, 384, 768), **kwargs)


def convnext_small(**kwargs):
    return _convnext((3, 3, 27, 3), (96, 192, 384, 768), **kwargs)


def convnext_base(**kwargs):
    return _convnext((3, 3, 27, 3), (128, 256, 512, 1024), **kwargs)


def convnext_large(**kwargs):
    return _convnext((3, 3, 27, 3), (192, 384, 768, 1536), **kwargs)
