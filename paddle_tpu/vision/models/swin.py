"""Swin Transformer (hierarchical windowed attention).

PaddleClas-era backbone (ppcls/arch/backbone/model_zoo/swin_transformer.py).
TPU notes: window partitioning is pure reshape/transpose (free under XLA
layout assignment); every window attends over a FIXED w*w=49 sequence, so
one attention shape serves all stages — no dynamic shapes, and the
(num_windows*B, 49, C) batch keeps the MXU fed. The shifted variant is
jnp.roll (a cheap static rotation) + an additive mask precomputed at
build time.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...core.tensor import Tensor
from ...ops.manipulation import roll


def _window_partition(x, w):
    # (B, H, W, C) -> (B*nW, w*w, C)
    B, H, W, C = x.shape
    x = x.reshape([B, H // w, w, W // w, w, C])
    x = x.transpose([0, 1, 3, 2, 4, 5])
    return x.reshape([-1, w * w, C])


def _window_reverse(x, w, H, W):
    B = x.shape[0] // (H * W // (w * w))
    x = x.reshape([B, H // w, W // w, w, w, -1])
    x = x.transpose([0, 1, 3, 2, 4, 5])
    return x.reshape([B, H, W, -1])


class WindowAttention(nn.Layer):
    """MSA within one window + learned relative position bias."""

    def __init__(self, dim, window, num_heads):
        super().__init__()
        self.dim = dim
        self.window = window
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        self.qkv = nn.Linear(dim, dim * 3)
        self.proj = nn.Linear(dim, dim)
        n = 2 * window - 1
        self.rpb_table = self.create_parameter(
            [n * n, num_heads],
            default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        # pairwise relative-position index, fixed at build
        coords = np.stack(np.meshgrid(np.arange(window), np.arange(window),
                                      indexing="ij"))        # (2, w, w)
        flat = coords.reshape(2, -1)                          # (2, w*w)
        rel = flat[:, :, None] - flat[:, None, :] + window - 1
        idx = (rel[0] * n + rel[1]).astype(np.int64)          # (w*w, w*w)
        self.register_buffer("rpb_index", Tensor(idx.reshape(-1)))

    def forward(self, x, mask=None):
        # x: (B_, N, C) with N = window*window
        B_, N, C = x.shape
        h = self.num_heads
        qkv = self.qkv(x).reshape([B_, N, 3, h, C // h])
        qkv = qkv.transpose([2, 0, 3, 1, 4])     # (3, B_, h, N, hd)
        q, k, v = qkv[0], qkv[1], qkv[2]
        attn = (q * self.scale) @ k.transpose([0, 1, 3, 2])  # (B_,h,N,N)
        bias = self.rpb_table[self.rpb_index].reshape([N, N, h])
        attn = attn + bias.transpose([2, 0, 1]).unsqueeze(0)
        if mask is not None:                      # (nW, N, N) additive
            nW = mask.shape[0]
            attn = attn.reshape([B_ // nW, nW, h, N, N]) \
                + mask.unsqueeze(1).unsqueeze(0)
            attn = attn.reshape([B_, h, N, N])
        attn = nn.functional.softmax(attn, axis=-1)
        out = (attn @ v).transpose([0, 2, 1, 3]).reshape([B_, N, C])
        return self.proj(out)


class SwinBlock(nn.Layer):
    def __init__(self, dim, input_resolution, num_heads, window=7,
                 shift=0, mlp_ratio=4.0):
        super().__init__()
        self.dim = dim
        self.resolution = input_resolution
        if min(input_resolution) <= window:
            window, shift = min(input_resolution), 0
        if input_resolution[0] % window or input_resolution[1] % window:
            raise ValueError(
                f"Swin: feature map {input_resolution} must be divisible "
                f"by window {window} at every stage — pick img_size/"
                f"patch_size so each stage resolution is a multiple of "
                f"the window (e.g. 224/4 with window 7)")
        self.window = window
        self.shift = shift
        self.norm1 = nn.LayerNorm(dim)
        self.attn = WindowAttention(dim, window, num_heads)
        self.norm2 = nn.LayerNorm(dim)
        self.mlp = nn.Sequential(nn.Linear(dim, int(dim * mlp_ratio)),
                                 nn.GELU(),
                                 nn.Linear(int(dim * mlp_ratio), dim))
        if shift > 0:
            self.register_buffer("attn_mask",
                                 Tensor(self._shift_mask()))
        else:
            self.attn_mask = None

    def _shift_mask(self):
        """Additive mask keeping attention within pre-shift regions
        (-100 between tokens whose windows wrapped differently)."""
        H, W = self.resolution
        w, s = self.window, self.shift
        img = np.zeros((1, H, W, 1), np.float32)
        cnt = 0
        for hs in (slice(0, -w), slice(-w, -s), slice(-s, None)):
            for ws in (slice(0, -w), slice(-w, -s), slice(-s, None)):
                img[:, hs, ws, :] = cnt
                cnt += 1
        win = img.reshape(1, H // w, w, W // w, w, 1)
        win = win.transpose(0, 1, 3, 2, 4, 5).reshape(-1, w * w)
        diff = win[:, :, None] - win[:, None, :]
        return np.where(diff != 0, -100.0, 0.0).astype(np.float32)

    def forward(self, x):
        H, W = self.resolution
        B, L, C = x.shape
        shortcut = x
        x = self.norm1(x).reshape([B, H, W, C])
        if self.shift > 0:
            x = roll(x, shifts=[-self.shift, -self.shift], axis=[1, 2])
        xw = _window_partition(x, self.window)
        xw = self.attn(xw, self.attn_mask)
        x = _window_reverse(xw, self.window, H, W)
        if self.shift > 0:
            x = roll(x, shifts=[self.shift, self.shift], axis=[1, 2])
        x = shortcut + x.reshape([B, L, C])
        return x + self.mlp(self.norm2(x))


class PatchMerging(nn.Layer):
    """Downsample 2x: concat 2x2 neighborhood -> LN -> Linear(4C->2C)."""

    def __init__(self, input_resolution, dim):
        super().__init__()
        self.resolution = input_resolution
        self.norm = nn.LayerNorm(4 * dim)
        self.reduction = nn.Linear(4 * dim, 2 * dim, bias_attr=False)

    def forward(self, x):
        H, W = self.resolution
        B, L, C = x.shape
        x = x.reshape([B, H // 2, 2, W // 2, 2, C])
        x = x.transpose([0, 1, 3, 2, 4, 5]).reshape(
            [B, (H // 2) * (W // 2), 4 * C])
        return self.reduction(self.norm(x))


class SwinTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=4, in_chans=3,
                 class_num=1000, embed_dim=96, depths=(2, 2, 6, 2),
                 num_heads=(3, 6, 12, 24), window=7, mlp_ratio=4.0):
        super().__init__()
        self.patch_embed = nn.Conv2D(in_chans, embed_dim, patch_size,
                                     stride=patch_size)
        res = img_size // patch_size
        self.norm0 = nn.LayerNorm(embed_dim)
        self.stages = nn.LayerList()
        self.merges = nn.LayerList()
        dim = embed_dim
        for si, (d, h) in enumerate(zip(depths, num_heads)):
            blocks = nn.Sequential(*[
                SwinBlock(dim, (res, res), h, window,
                          shift=0 if i % 2 == 0 else window // 2,
                          mlp_ratio=mlp_ratio) for i in range(d)])
            self.stages.append(blocks)
            if si < len(depths) - 1:
                self.merges.append(PatchMerging((res, res), dim))
                dim *= 2
                res //= 2
        self.norm = nn.LayerNorm(dim)
        self.head = nn.Linear(dim, class_num)

    def forward(self, x):
        x = self.patch_embed(x)                  # (B, C, H', W')
        B, C = x.shape[0], x.shape[1]
        x = x.reshape([B, C, -1]).transpose([0, 2, 1])
        x = self.norm0(x)
        for si, stage in enumerate(self.stages):
            x = stage(x)
            if si < len(self.merges):
                x = self.merges[si](x)
        x = self.norm(x).mean(axis=1)            # global pool over tokens
        return self.head(x)


def swin_tiny_patch4_window7_224(**kwargs):
    return SwinTransformer(depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24),
                           embed_dim=96, **kwargs)


def swin_small_patch4_window7_224(**kwargs):
    return SwinTransformer(depths=(2, 2, 18, 2), num_heads=(3, 6, 12, 24),
                           embed_dim=96, **kwargs)


def swin_base_patch4_window7_224(**kwargs):
    return SwinTransformer(depths=(2, 2, 18, 2), num_heads=(4, 8, 16, 32),
                           embed_dim=128, **kwargs)
