"""Additional vision model families.

~ python/paddle/vision/models/{alexnet,squeezenet,shufflenetv2,densenet,
mobilenetv1}.py — the remaining hapi model-zoo capability slots.
"""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kw):
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1(s)),
                       self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.1", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.stride = stride
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act_layer())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer())

    def forward(self, x):
        from ...nn.functional import channel_shuffle
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, act="relu"):
        super().__init__()
        stage_out = {0.25: [24, 24, 48, 96, 512],
                     0.33: [24, 32, 64, 128, 512],
                     0.5: [24, 48, 96, 192, 1024],
                     1.0: [24, 116, 232, 464, 1024],
                     1.5: [24, 176, 352, 704, 1024],
                     2.0: [24, 244, 488, 976, 2048]}[scale]
        repeats = [4, 8, 4]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, stage_out[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(stage_out[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = stage_out[0]
        for i, r in enumerate(repeats):
            out_c = stage_out[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            for _ in range(r - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, stage_out[-1], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[-1]), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.pool(self.conv5(x))
        return self.fc(flatten(x, 1))


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(1.0, act="swish", **kw)


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.body = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        return concat([x, self.body(x)], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000):
        super().__init__()
        cfg = {121: [6, 12, 24, 16], 161: [6, 12, 36, 24],
               169: [6, 12, 32, 32], 201: [6, 12, 48, 32],
               264: [6, 12, 64, 48]}[layers]
        c = 64
        feats = [nn.Conv2D(3, c, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1)]
        for i, n in enumerate(cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(cfg) - 1:
                feats.extend([nn.BatchNorm2D(c), nn.ReLU(),
                              nn.Conv2D(c, c // 2, 1, bias_attr=False),
                              nn.AvgPool2D(2, 2)])
                c //= 2
        feats.extend([nn.BatchNorm2D(c), nn.ReLU()])
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(flatten(x, 1))


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, growth_rate=48, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c), nn.ReLU(),
                nn.Conv2D(in_c, out_c, 1, bias_attr=False),
                nn.BatchNorm2D(out_c), nn.ReLU())

        def c(v):
            return max(8, int(v * scale))

        self.net = nn.Sequential(
            nn.Conv2D(3, c(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c(32)), nn.ReLU(),
            dw_sep(c(32), c(64), 1), dw_sep(c(64), c(128), 2),
            dw_sep(c(128), c(128), 1), dw_sep(c(128), c(256), 2),
            dw_sep(c(256), c(256), 1), dw_sep(c(256), c(512), 2),
            *[dw_sep(c(512), c(512), 1) for _ in range(5)],
            dw_sep(c(512), c(1024), 2), dw_sep(c(1024), c(1024), 1),
            nn.AdaptiveAvgPool2D((1, 1)))
        self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        return self.fc(flatten(self.net(x), 1))


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale, **kw)
