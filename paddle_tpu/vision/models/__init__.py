"""Vision models. ~ python/paddle/vision/models/."""
from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, wide_resnet50_2,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .small_nets import (  # noqa: F401
    AlexNet, DenseNet, MobileNetV1, ShuffleNetV2, SqueezeNet, alexnet,
    densenet121, mobilenet_v1, shufflenet_v2_x1_0, squeezenet1_1,
)
