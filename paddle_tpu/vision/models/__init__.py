"""Vision models. ~ python/paddle/vision/models/."""
from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    resnext50_32x4d, resnext50_64x4d, resnext101_32x4d, resnext101_64x4d,
    resnext152_32x4d, resnext152_64x4d, wide_resnet50_2, wide_resnet101_2,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .small_nets import (  # noqa: F401
    AlexNet, DenseNet, MobileNetV1, ShuffleNetV2, SqueezeNet, alexnet,
    densenet121, densenet161, densenet169, densenet201, densenet264,
    mobilenet_v1, shufflenet_v2_swish, shufflenet_v2_x0_25,
    shufflenet_v2_x0_33, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0, squeezenet1_0, squeezenet1_1,
)
from .extra_nets import (  # noqa: F401
    GoogLeNet, InceptionV3, MobileNetV3Large, MobileNetV3Small, googlenet,
    inception_v3, mobilenet_v3_large, mobilenet_v3_small,
)
from .vit import (  # noqa: F401
    VisionTransformer, vit_base_patch16_224, vit_base_patch32_224,
    vit_large_patch16_224, vit_small_patch16_224, vit_tiny_patch16_224,
)
from .convnext import (  # noqa: F401
    ConvNeXt, convnext_base, convnext_large, convnext_small,
    convnext_tiny,
)
from .swin import (  # noqa: F401
    SwinTransformer, swin_base_patch4_window7_224,
    swin_small_patch4_window7_224, swin_tiny_patch4_window7_224,
)
