"""ResNet family. ~ python/paddle/vision/models/resnet.py."""
from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, norm_layer=norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet_train_step_factory(model, mesh, learning_rate=0.1, momentum=0.9,
                              weight_decay=1e-4):
    """Compiled SGD-momentum train step for the ResNet family —
    BASELINE.md config 2 (PaddleClas ResNet-50 recipe:
    ~ python/paddle/vision/models/resnet.py + Momentum optimizer,
    python/paddle/optimizer/momentum.py): CE loss, L2-coupled decay.

    Returns ``(params, buffers, opt_state, step)`` where
    ``step(params, buffers, opt_state, images, labels) ->
    (params, buffers, opt_state, loss)``. BatchNorm running stats are
    threaded FUNCTIONALLY: the forward runs in training mode, the
    traced stat updates are read back off the model and returned as the
    new ``buffers`` — same pattern the reference implements with
    mutable mean/variance op outputs (phi batch_norm kernel). Under a
    >1 'data' mesh axis the batch is sharded and XLA computes global
    batch stats (SyncBatchNorm semantics for free).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...autograd import no_grad
    from ...core.tensor import Tensor

    param_names = {name for name, _ in model.named_parameters()}
    state = model.state_dict()
    rep = NamedSharding(mesh, P())
    data_axis = "data" if "data" in mesh.axis_names else None
    data_sh = NamedSharding(mesh, P(data_axis))
    params = {k: jax.device_put(jnp.array(v._value, copy=True), rep)
              for k, v in state.items() if k in param_names}
    # stat buffers ride in f32 even for a bf16-cast model (Layer.to casts
    # float buffers — torch/paddle semantics — but momentum-blended
    # running stats degrade fast in bf16; batch_norm computes f32
    # internally either way)
    buffers = {
        k: jax.device_put(
            jnp.array(v._value, copy=True).astype(jnp.float32)
            if jnp.issubdtype(v._value.dtype, jnp.floating)
            else jnp.array(v._value, copy=True), rep)
        for k, v in state.items() if k not in param_names}
    # low-precision params get f32 masters (velocity alone is not enough:
    # re-quantizing the weight each step loses any update below ~2^-9 of
    # its magnitude, freezing weights once grads shrink)
    low_prec = {k for k, v in params.items() if v.dtype != jnp.float32}
    opt_state = {
        "step": jax.device_put(jnp.zeros((), jnp.int32), rep),
        "velocity": {k: jax.device_put(jnp.zeros(v.shape, jnp.float32), rep)
                     for k, v in params.items()},
        "master": {k: jax.device_put(params[k].astype(jnp.float32), rep)
                   for k in sorted(low_prec)},
    }

    def forward_loss(params, buffers, images, labels):
        saved = model.tree_flatten_params()
        was_training = model.training
        model.train()
        try:
            with no_grad():  # jax.grad differentiates; the tape must not
                model.load_tree({**params, **buffers})
                logits = model(Tensor(images))._value
                # training-mode BN rebound the stat buffers to traced
                # values — read the updates back off the model
                sd = model.state_dict()
                new_buffers = {k: sd[k]._value for k in buffers}
        finally:
            model.load_tree(saved)
            if not was_training:
                model.eval()
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, labels[:, None], -1)[:, 0])
        return loss, new_buffers

    def train_step(params, buffers, opt_state, images, labels):
        (loss, new_buffers), grads = jax.value_and_grad(
            forward_loss, has_aux=True)(params, buffers, images, labels)
        new_p, new_vel, new_master = {}, {}, {}
        for k in params:
            p32 = opt_state["master"][k] if k in low_prec \
                else params[k].astype(jnp.float32)
            g = grads[k].astype(jnp.float32) + weight_decay * p32
            v = momentum * opt_state["velocity"][k] + g
            new_vel[k] = v
            p32 = p32 - learning_rate * v
            if k in low_prec:
                new_master[k] = p32
            new_p[k] = p32.astype(params[k].dtype)
        return (new_p, new_buffers,
                {"step": opt_state["step"] + 1, "velocity": new_vel,
                 "master": new_master}, loss)

    param_sh = {k: rep for k in params}
    buf_sh = {k: rep for k in buffers}
    state_sh = {"step": rep, "velocity": {k: rep for k in params},
                "master": {k: rep for k in sorted(low_prec)}}
    jitted = jax.jit(
        train_step,
        in_shardings=(param_sh, buf_sh, state_sh, data_sh, data_sh),
        out_shardings=(param_sh, buf_sh, state_sh, rep),
        donate_argnums=(0, 1, 2))
    return params, buffers, opt_state, jitted


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=64, width=4, **kwargs)
