"""Vision datasets.

~ python/paddle/vision/datasets/ (mnist.py, cifar.py, ImageFolder). Zero
egress environment: loaders read standard local files (IDX/pickle formats)
when present; MNIST additionally has a deterministic synthetic fallback so
the LeNet smoke config runs anywhere.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset

_SEARCH_DIRS = [
    os.path.expanduser("~/.cache/paddle_tpu/datasets"),
    "/root/data", "/data", "/tmp/datasets",
]


def _find(fname):
    for d in _SEARCH_DIRS:
        p = os.path.join(d, fname)
        if os.path.exists(p):
            return p
    return None


def _read_idx(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _synthetic_digits(n, seed):
    """Deterministic separable 28x28 'digits': class-dependent stripe+blob
    patterns + noise. Linearly separable enough for >98% train accuracy —
    serves the smoke-test role of MNIST when no local copy exists."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    images = np.zeros((n, 28, 28), dtype=np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for c in range(10):
        mask = labels == c
        k = int(mask.sum())
        if k == 0:
            continue
        base = (np.sin(xx * (c + 1) * 0.35) + np.cos(yy * (c + 2) * 0.3))
        cx, cy = 6 + (c % 5) * 4, 6 + (c // 5) * 12
        blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 18.0))
        pattern = (0.5 * base + 2.0 * blob).astype(np.float32)
        images[mask] = pattern[None] + rng.normal(
            0, 0.3, size=(k, 28, 28)).astype(np.float32)
    images = (images - images.min()) / (images.max() - images.min() + 1e-6)
    return (images * 255).astype(np.uint8), labels.astype(np.int64)


class MNIST(Dataset):
    """~ python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        prefix = "train" if mode == "train" else "t10k"
        img = image_path or _find(f"{prefix}-images-idx3-ubyte.gz") \
            or _find(f"{prefix}-images-idx3-ubyte")
        lab = label_path or _find(f"{prefix}-labels-idx1-ubyte.gz") \
            or _find(f"{prefix}-labels-idx1-ubyte")
        if img and lab:
            self.images = _read_idx(img)
            self.labels = _read_idx(lab).astype(np.int64)
        else:
            n = 60000 if mode == "train" else 10000
            self.images, self.labels = _synthetic_digits(
                n, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # (1,28,28)
        img = img / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """~ python/paddle/vision/datasets/cifar.py. Local pickle batches or
    synthetic fallback."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        import pickle
        found = data_file or _find("cifar-10-batches-py")
        if found and os.path.isdir(found):
            xs, ys = [], []
            names = [f"data_batch_{i}" for i in range(1, 6)] \
                if mode == "train" else ["test_batch"]
            for nme in names:
                with open(os.path.join(found, nme), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"])
                ys.extend(d[b"labels"])
            self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
            self.labels = np.asarray(ys, dtype=np.int64)
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            n = 50000 if mode == "train" else 10000
            self.labels = rng.integers(0, 10, n).astype(np.int64)
            self.images = rng.integers(
                0, 255, (n, 3, 32, 32)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    pass


class ImageFolder(Dataset):
    """Directory-of-class-dirs loader (~ vision/datasets/folder.py)."""

    def __init__(self, root, transform=None, loader=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith((".npy", ".png", ".jpg", ".jpeg")):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        if path.endswith(".npy"):
            img = np.load(path).astype(np.float32)
        else:
            from PIL import Image
            img = np.asarray(Image.open(path), dtype=np.float32) / 255.0
            if img.ndim == 3:
                img = img.transpose(2, 0, 1)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class DatasetFolder(ImageFolder):
    """~ vision/datasets/folder.py DatasetFolder: class-subdirectory layout
    -> (sample, class_idx). (ImageFolder above already implements this
    layout; the reference's flat ImageFolder variant is the loader=None
    case of paddle.vision.image_load over a file list.)"""


class Flowers(Dataset):
    """~ vision/datasets/flowers.py (102-category flowers); local copy or
    deterministic synthetic fallback (zero-egress env)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/flowers.npz")
        if os.path.exists(local):
            d = np.load(local)
            self.x = d[f"x_{mode}"]
            self.y = d[f"y_{mode}"]
        else:
            rng = np.random.default_rng(11 if mode == "train" else 12)
            n = 1020 if mode == "train" else 102
            self.x = rng.random((n, 3, 32, 32), np.float32)
            self.y = np.tile(np.arange(102), n // 102 + 1)[:n].astype(
                np.int64)

    def __getitem__(self, i):
        img = self.x[i]
        if self.transform:
            img = self.transform(img)
        return img, self.y[i]

    def __len__(self):
        return len(self.x)


class VOC2012(Dataset):
    """~ vision/datasets/voc2012.py (segmentation pairs); local copy or
    synthetic image/mask pairs."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        local = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/voc2012.npz")
        if os.path.exists(local):
            d = np.load(local)
            self.x = d[f"x_{mode}"]
            self.y = d[f"y_{mode}"]
        else:
            rng = np.random.default_rng(13 if mode == "train" else 14)
            n = 128 if mode == "train" else 32
            self.x = rng.random((n, 3, 64, 64), np.float32)
            self.y = rng.integers(0, 21, (n, 64, 64)).astype(np.int64)

    def __getitem__(self, i):
        img = self.x[i]
        if self.transform:
            img = self.transform(img)
        return img, self.y[i]

    def __len__(self):
        return len(self.x)
