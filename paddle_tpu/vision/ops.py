"""Vision ops: nms, roi_align, box utilities.

~ python/paddle/vision/ops.py over the reference's detection op set
(paddle/fluid/operators/detection/). TPU note: nms is data-dependent; the
jit-friendly form returns a fixed-size keep mask (callers slice on host).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply_op("box_area", fn, boxes)


def box_iou(boxes1, boxes2):
    def fn(a, b):
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        return inter / (area_a[:, None] + area_b[None] - inter + 1e-10)
    return apply_op("box_iou", fn, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices (host-side, dynamic length —
    mirrors the reference's dynamic-output nms)."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores._value if isinstance(scores, Tensor) else scores)
         if scores is not None else np.ones(len(b), np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        if top_k is not None and len(keep) >= top_k:
            break
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        iou = inter / (area_i + areas - inter + 1e-10)
        same_cat = np.ones(len(b), bool)
        if category_idxs is not None:
            cat = np.asarray(category_idxs._value
                             if isinstance(category_idxs, Tensor)
                             else category_idxs)
            same_cat = cat == cat[i]
        suppressed |= (iou > iou_threshold) & same_cat
    return Tensor(np.asarray(keep, np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear gather (jit-friendly; ~ roi_align op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois):
        # feat: (N,C,H,W); rois: (R,4) in input coords; all rois on image 0
        # (multi-image routing via boxes_num handled by caller slicing)
        N, Cc, H, W = feat.shape
        R = rois.shape[0]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = (y1[:, None] + (jnp.arange(oh) + 0.5)[None] * rh[:, None] / oh)
        xs = (x1[:, None] + (jnp.arange(ow) + 0.5)[None] * rw[:, None] / ow)
        img0 = feat[0]

        def one_roi(ygrid, xgrid):
            yy0 = jnp.clip(jnp.floor(ygrid).astype(jnp.int32), 0, H - 1)
            xx0 = jnp.clip(jnp.floor(xgrid).astype(jnp.int32), 0, W - 1)
            yy1 = jnp.clip(yy0 + 1, 0, H - 1)
            xx1 = jnp.clip(xx0 + 1, 0, W - 1)
            fy = ygrid - yy0
            fx = xgrid - xx0
            i00 = img0[:, yy0][:, :, xx0]
            i01 = img0[:, yy0][:, :, xx1]
            i10 = img0[:, yy1][:, :, xx0]
            i11 = img0[:, yy1][:, :, xx1]
            top = i00 * (1 - fx)[None, None, :] + i01 * fx[None, None, :]
            bot = i10 * (1 - fx)[None, None, :] + i11 * fx[None, None, :]
            return top * (1 - fy)[None, :, None] + bot * fy[None, :, None]

        return jax.vmap(one_roi)(ys, xs)  # (R, C, oh, ow)
    return apply_op("roi_align", fn, x, boxes)


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: irregular gather pattern — planned as a Pallas "
        "kernel; use roi_align/grid-sample style gathers meanwhile")
