"""Vision ops: nms, roi_align, box utilities.

~ python/paddle/vision/ops.py over the reference's detection op set
(paddle/fluid/operators/detection/). TPU note: nms is data-dependent; the
jit-friendly form returns a fixed-size keep mask (callers slice on host).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply_op("box_area", fn, boxes)


def box_iou(boxes1, boxes2):
    def fn(a, b):
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        return inter / (area_a[:, None] + area_b[None] - inter + 1e-10)
    return apply_op("box_iou", fn, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices (host-side, dynamic length —
    mirrors the reference's dynamic-output nms)."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores._value if isinstance(scores, Tensor) else scores)
         if scores is not None else np.ones(len(b), np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        if top_k is not None and len(keep) >= top_k:
            break
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        iou = inter / (area_i + areas - inter + 1e-10)
        same_cat = np.ones(len(b), bool)
        if category_idxs is not None:
            cat = np.asarray(category_idxs._value
                             if isinstance(category_idxs, Tensor)
                             else category_idxs)
            same_cat = cat == cat[i]
        suppressed |= (iou > iou_threshold) & same_cat
    return Tensor(np.asarray(keep, np.int64))


def _roi_batch_index(boxes_num, N, R):
    """Per-RoI image index from boxes_num (RoIs are listed image-major)."""
    if boxes_num is None:
        return jnp.zeros((R,), jnp.int32)
    bn = jnp.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                     else boxes_num, jnp.int32)
    return jnp.repeat(jnp.arange(N, dtype=jnp.int32), bn,
                      total_repeat_length=R)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear gather (jit-friendly; ~ roi_align op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois):
        # feat: (N,C,H,W); rois: (R,4) in input coords, image-major order;
        # each RoI is routed to its image via boxes_num
        N, Cc, H, W = feat.shape
        R = rois.shape[0]
        bidx = _roi_batch_index(boxes_num, N, R)
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = (y1[:, None] + (jnp.arange(oh) + 0.5)[None] * rh[:, None] / oh)
        xs = (x1[:, None] + (jnp.arange(ow) + 0.5)[None] * rw[:, None] / ow)

        def one_roi(ygrid, xgrid, b):
            img = feat[b]
            yy0 = jnp.clip(jnp.floor(ygrid).astype(jnp.int32), 0, H - 1)
            xx0 = jnp.clip(jnp.floor(xgrid).astype(jnp.int32), 0, W - 1)
            yy1 = jnp.clip(yy0 + 1, 0, H - 1)
            xx1 = jnp.clip(xx0 + 1, 0, W - 1)
            fy = ygrid - yy0
            fx = xgrid - xx0
            i00 = img[:, yy0][:, :, xx0]
            i01 = img[:, yy0][:, :, xx1]
            i10 = img[:, yy1][:, :, xx0]
            i11 = img[:, yy1][:, :, xx1]
            top = i00 * (1 - fx)[None, None, :] + i01 * fx[None, None, :]
            bot = i10 * (1 - fx)[None, None, :] + i11 * fx[None, None, :]
            return top * (1 - fy)[None, :, None] + bot * fy[None, :, None]

        return jax.vmap(one_roi)(ys, xs, bidx)  # (R, C, oh, ow)
    return apply_op("roi_align", fn, x, boxes)


def _roi_grid(rois, spatial_scale, oh, ow, H, W):
    x1 = rois[:, 0] * spatial_scale
    y1 = rois[:, 1] * spatial_scale
    x2 = rois[:, 2] * spatial_scale
    y2 = rois[:, 3] * spatial_scale
    return x1, y1, jnp.maximum(x2 - x1, 1.0), jnp.maximum(y2 - y1, 1.0)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """~ paddle.vision.ops.roi_pool (max pooling inside each RoI bin)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois):
        N, C, H, W = feat.shape
        bidx = _roi_batch_index(boxes_num, N, rois.shape[0])
        x1, y1, rw, rh = _roi_grid(rois, spatial_scale, oh, ow, H, W)

        def one_roi(px1, py1, prw, prh, b):
            img = feat[b]
            # integer bin boundaries like the reference's roi_pool
            ys = py1 + jnp.arange(oh + 1) * prh / oh
            xs = px1 + jnp.arange(ow + 1) * prw / ow
            ys = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H)
            xs = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W)
            yy = jnp.arange(H)
            xx = jnp.arange(W)

            def bin_max(i, j):
                row_m = (yy >= ys[i]) & (yy < jnp.maximum(ys[i + 1],
                                                          ys[i] + 1))
                col_m = (xx >= xs[j]) & (xx < jnp.maximum(xs[j + 1],
                                                          xs[j] + 1))
                m = row_m[:, None] & col_m[None, :]
                neg = jnp.finfo(img.dtype).min
                return jnp.max(jnp.where(m[None], img, neg), axis=(1, 2))

            rows = []
            for i in range(oh):
                cols = [bin_max(i, j) for j in range(ow)]
                rows.append(jnp.stack(cols, -1))
            return jnp.stack(rows, -2)  # (C, oh, ow)
        return jax.vmap(one_roi)(x1, y1, rw, rh, bidx)
    return apply_op("roi_pool", fn, x, boxes)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """~ paddle.vision.ops.psroi_pool (position-sensitive RoI average pool,
    R-FCN): input channels = C_out * oh * ow; bin (i, j) of output channel c
    averages input channel c*oh*ow + i*ow + j inside that bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois):
        N, C, H, W = feat.shape
        c_out = C // (oh * ow)
        bidx = _roi_batch_index(boxes_num, N, rois.shape[0])
        x1, y1, rw, rh = _roi_grid(rois, spatial_scale, oh, ow, H, W)

        def one_roi(px1, py1, prw, prh, b):
            img = feat[b]
            ys = py1 + jnp.arange(oh + 1) * prh / oh
            xs = px1 + jnp.arange(ow + 1) * prw / ow
            yy = jnp.arange(H)
            xx = jnp.arange(W)
            out = []
            for i in range(oh):
                row = []
                for j in range(ow):
                    row_m = (yy + 0.5 >= ys[i]) & (yy + 0.5 <= ys[i + 1])
                    col_m = (xx + 0.5 >= xs[j]) & (xx + 0.5 <= xs[j + 1])
                    m = (row_m[:, None] & col_m[None, :]).astype(img.dtype)
                    cnt = jnp.maximum(jnp.sum(m), 1.0)
                    chans = img[jnp.arange(c_out) * (oh * ow) + i * ow + j]
                    row.append(jnp.sum(chans * m[None], axis=(1, 2)) / cnt)
                out.append(jnp.stack(row, -1))
            return jnp.stack(out, -2)  # (c_out, oh, ow)
        return jax.vmap(one_roi)(x1, y1, rw, rh, bidx)
    return apply_op("psroi_pool", fn, x, boxes)


def _bilinear_sample_nchw(img, ygrid, xgrid):
    """img: (C,H,W); grids: arbitrary equal shapes -> (C, *grid.shape).

    Zero-padding semantics per-CORNER, matching the reference's deformable
    im2col (deformable_conv_op.cu dmcn_im2col_bilinear): an out-of-bounds
    corner contributes 0 while in-bounds corners keep their weights — NOT
    the replicate-padding that clipping all four corners would give. A
    position fully outside (-1, size) has no valid corner and samples 0.
    """
    C, H, W = img.shape
    y0 = jnp.floor(ygrid).astype(jnp.int32)
    x0 = jnp.floor(xgrid).astype(jnp.int32)
    fy = ygrid - y0
    fx = xgrid - x0

    def corner(yy, xx, wgt):
        valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        yc = jnp.clip(yy, 0, H - 1)
        xc = jnp.clip(xx, 0, W - 1)
        return img[:, yc, xc] * (wgt * valid.astype(img.dtype))

    return (corner(y0, x0, (1 - fy) * (1 - fx))
            + corner(y0, x0 + 1, (1 - fy) * fx)
            + corner(y0 + 1, x0, fy * (1 - fx))
            + corner(y0 + 1, x0 + 1, fy * fx))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """~ paddle.vision.ops.deform_conv2d
    (operators/deformable_conv_op.cu): each kernel tap samples the input at
    its regular position plus a learned per-pixel offset (v2 adds a
    modulation mask). TPU lowering: one bilinear gather per kernel tap
    (kh*kw fused gathers) followed by a dense 1x1 contraction on the MXU —
    no im2col buffer materialized."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def fn(xv, off, w, *rest):
        maskv = None
        biasv = None
        ri = 0
        if mask is not None:
            maskv = rest[ri]
            ri += 1
        if bias is not None:
            biasv = rest[ri]
        B, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Ho = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        Wo = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        base_y = jnp.arange(Ho) * st[0] - pd[0]
        base_x = jnp.arange(Wo) * st[1] - pd[1]
        # offsets: (B, 2*dg*kh*kw, Ho, Wo) ordered (y, x) per tap
        off = off.reshape(B, deformable_groups, kh * kw, 2, Ho, Wo)
        if maskv is not None:
            maskv = maskv.reshape(B, deformable_groups, kh * kw, Ho, Wo)
        cg = Cin // deformable_groups

        def per_image(img, off_b, mask_b):
            cols = []
            for k in range(kh * kw):
                ky, kx = divmod(k, kw)
                groups_out = []
                for dg in range(deformable_groups):
                    yg = (base_y[:, None] + ky * dl[0]
                          + off_b[dg, k, 0])
                    xg = (base_x[None, :] + kx * dl[1]
                          + off_b[dg, k, 1])
                    sub = img[dg * cg:(dg + 1) * cg]
                    samp = _bilinear_sample_nchw(sub, yg, xg)
                    if mask_b is not None:
                        samp = samp * mask_b[dg, k][None]
                    groups_out.append(samp)
                cols.append(jnp.concatenate(groups_out, 0))  # (Cin,Ho,Wo)
            return jnp.stack(cols, 1)  # (Cin, kh*kw, Ho, Wo)

        cols = jax.vmap(per_image)(
            xv, off,
            maskv if maskv is not None else jnp.zeros((B, 0, 0, 0, 0)),
        ) if maskv is not None else jax.vmap(
            lambda img, off_b: per_image(img, off_b, None))(xv, off)
        # contraction: out[b,o,h,w] = sum_{ci,k} w[o,ci,k] * cols[b,ci,k,h,w]
        wf = w.reshape(Cout, Cin_g * kh * kw)
        if groups == 1:
            colsf = cols.reshape(B, Cin * kh * kw, Ho, Wo)
            out = jnp.einsum("ok,bkhw->bohw", wf, colsf)
        else:
            og = Cout // groups
            outs = []
            for g in range(groups):
                colsg = cols[:, g * Cin_g:(g + 1) * Cin_g].reshape(
                    B, Cin_g * kh * kw, Ho, Wo)
                outs.append(jnp.einsum(
                    "ok,bkhw->bohw", wf[g * og:(g + 1) * og], colsg))
            out = jnp.concatenate(outs, 1)
        if biasv is not None:
            out = out + biasv[None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op("deform_conv2d", fn, *args)


class DeformConv2D:
    """~ paddle.nn / paddle.vision.ops.DeformConv2D layer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from ..core.tensor import Parameter
        from ..core.generator import default_generator
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = (stride, padding, dilation, deformable_groups, groups)
        fan_in = in_channels * ks[0] * ks[1] // groups
        limit = float(np.sqrt(6.0 / max(1, fan_in)))
        key = default_generator().next_key()
        self.weight = Parameter(jax.random.uniform(
            key, (out_channels, in_channels // groups) + ks,
            jnp.float32, -limit, limit))
        self.bias = Parameter(jnp.zeros((out_channels,))) \
            if bias_attr is not False else None

    def __call__(self, x, offset, mask=None):
        st, pd, dl, dg, g = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias, st, pd, dl,
                             dg, g, mask)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def read_file(filename, name=None):
    """~ paddle.vision.ops.read_file — raw bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, dtype=np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """~ paddle.vision.ops.decode_jpeg (CPU-side decode; the reference uses
    nvjpeg on GPU — host decode feeds the TPU input pipeline)."""
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg requires Pillow on the host") from e
    import io as _io
    buf = np.asarray(x._value if isinstance(x, Tensor) else x,
                     dtype=np.uint8).tobytes()
    img = Image.open(_io.BytesIO(buf))
    if mode.lower() == "gray":
        img = img.convert("L")
    elif mode.lower() == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """~ paddle.vision.ops.yolo_box (operators/detection/yolo_box_op): decode
    YOLOv3 head predictions into boxes + per-class scores."""
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = len(an)

    def fn(xv, imgs):
        B, C, H, W = xv.shape
        p = xv.reshape(B, na, 5 + class_num, H, W)
        gx = (jnp.arange(W)[None, :] + 0.0)
        gy = (jnp.arange(H)[:, None] + 0.0)
        sig = jax.nn.sigmoid
        bx = (sig(p[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / W
        by = (sig(p[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / H
        bw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] \
            / (W * downsample_ratio)
        bh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] \
            / (H * downsample_ratio)
        conf = sig(p[:, :, 4])
        cls = sig(p[:, :, 5:])
        score = conf[:, :, None] * cls
        # to corner coords scaled by image size
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(B, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(B, -1, class_num)
        keep = (conf > conf_thresh).reshape(B, -1)
        boxes = boxes * keep[..., None]
        scores = scores * keep[..., None]
        return boxes, scores
    return apply_op("yolo_box", fn, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio=32, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """~ paddle.vision.ops.yolo_loss (operators/detection/yolov3_loss_op):
    YOLOv3 training loss — best-anchor assignment per gt, bce objectness
    with ignore region, l1/bce box terms, bce class term."""
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    amask = list(anchor_mask)
    an = an_all[amask]
    na = len(amask)

    def fn(xv, gtb, gtl):
        B, C, H, W = xv.shape
        p = xv.reshape(B, na, 5 + class_num, H, W)
        sig = jax.nn.sigmoid
        # decode predicted objectness for the ignore mask
        bx = (sig(p[:, :, 0]) + jnp.arange(W)[None, :]) / W
        by = (sig(p[:, :, 1]) + jnp.arange(H)[:, None]) / H
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) \
            * an[None, :, 0, None, None] / (W * downsample_ratio)
        bh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) \
            * an[None, :, 1, None, None] / (H * downsample_ratio)
        # gt: (B, G, 4) cxcywh normalized; labels: (B, G)
        G = gtb.shape[1]
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)

        # iou of each pred box with each gt (for ignore mask)
        pb = jnp.stack([bx - bw / 2, by - bh / 2, bx + bw / 2, by + bh / 2],
                       -1).reshape(B, -1, 4)
        gb = jnp.stack([gtb[..., 0] - gtb[..., 2] / 2,
                        gtb[..., 1] - gtb[..., 3] / 2,
                        gtb[..., 0] + gtb[..., 2] / 2,
                        gtb[..., 1] + gtb[..., 3] / 2], -1)
        lt = jnp.maximum(pb[:, :, None, :2], gb[:, None, :, :2])
        rb = jnp.minimum(pb[:, :, None, 2:], gb[:, None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        pa = (pb[..., 2] - pb[..., 0]) * (pb[..., 3] - pb[..., 1])
        ga = (gb[..., 2] - gb[..., 0]) * (gb[..., 3] - gb[..., 1])
        iou = inter / (pa[:, :, None] + ga[:, None] - inter + 1e-10)
        iou = jnp.where(valid[:, None, :], iou, 0.0)
        best_iou = jnp.max(iou, -1).reshape(B, na, H, W)
        ignore = best_iou > ignore_thresh

        # best anchor (within this mask) per gt by wh-iou
        gw = gtb[..., 2] * W * downsample_ratio
        gh = gtb[..., 3] * H * downsample_ratio
        inter_a = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
                   * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
        union_a = (gw * gh)[..., None] \
            + (an_all[:, 0] * an_all[:, 1])[None, None] - inter_a
        anchor_iou = inter_a / (union_a + 1e-10)
        best_anchor = jnp.argmax(anchor_iou, -1)  # (B, G) global anchor idx

        # targets on the grid
        gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)

        obj_t = jnp.zeros((B, na, H, W))
        tx = jnp.zeros((B, na, H, W))
        ty = jnp.zeros((B, na, H, W))
        tw = jnp.zeros((B, na, H, W))
        th = jnp.zeros((B, na, H, W))
        tcls = jnp.zeros((B, na, class_num, H, W))
        bidx = jnp.arange(B)[:, None].repeat(G, 1)
        for mi, a_global in enumerate(amask):
            sel = valid & (best_anchor == a_global)
            w_sel = sel.astype(jnp.float32)
            obj_t = obj_t.at[bidx, mi, gj, gi].max(w_sel)
            tx = tx.at[bidx, mi, gj, gi].add(
                w_sel * (gtb[..., 0] * W - gi))
            ty = ty.at[bidx, mi, gj, gi].add(
                w_sel * (gtb[..., 1] * H - gj))
            tw = tw.at[bidx, mi, gj, gi].add(w_sel * jnp.log(
                jnp.maximum(gw / an_all[a_global, 0], 1e-9)))
            th = th.at[bidx, mi, gj, gi].add(w_sel * jnp.log(
                jnp.maximum(gh / an_all[a_global, 1], 1e-9)))
            tcls = tcls.at[bidx, mi, gtl.astype(jnp.int32), gj, gi].max(
                w_sel)

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target \
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))

        pos = obj_t
        scale = 2.0 - (tw * 0 + 1) * 0  # box loss weight ~ (2 - w*h) omitted
        loss_xy = pos * (bce(p[:, :, 0], tx) + bce(p[:, :, 1], ty))
        loss_wh = pos * (jnp.abs(p[:, :, 2] - tw)
                         + jnp.abs(p[:, :, 3] - th))
        noobj = (1 - pos) * (1 - ignore.astype(jnp.float32))
        loss_obj = pos * bce(p[:, :, 4], jnp.ones_like(pos)) \
            + noobj * bce(p[:, :, 4], jnp.zeros_like(pos))
        loss_cls = pos[:, :, None] * bce(p[:, :, 5:], tcls)
        total = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
                 + loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))
        return total
    return apply_op("yolo_loss", fn, x, gt_box, gt_label)
