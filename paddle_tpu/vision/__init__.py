"""paddle_tpu.vision — models/transforms/datasets.

~ python/paddle/vision/ (11.3k LoC: 13 model families, transforms,
MNIST/Cifar/... datasets).
"""
from . import datasets  # noqa: F401
from . import detection  # noqa: F401
from . import detection_jit  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401

from .datasets import DatasetFolder, Flowers, VOC2012  # noqa: F401

_image_backend = "pil"


def set_image_backend(backend: str):
    """~ paddle.vision.set_image_backend ('pil' | 'cv2' | 'tensor')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """~ paddle.vision.image_load — decode an image file via the configured
    host backend (PIL; 'tensor' returns a CHW float Tensor)."""
    import numpy as np
    b = backend or _image_backend
    from PIL import Image
    img = Image.open(path)
    if b == "pil":
        return img
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    if b == "tensor":
        from ..core.tensor import Tensor
        return Tensor(arr.astype(np.float32) / 255.0)
    return arr  # cv2-style ndarray
