"""paddle_tpu.vision — models/transforms/datasets.

~ python/paddle/vision/ (11.3k LoC: 13 model families, transforms,
MNIST/Cifar/... datasets).
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
