"""Version-bridging wrappers for jax APIs that were renamed in flight.

The chip image carries a newer jax (``jax.shard_map`` with
``check_vma``/``axis_names``, ``pltpu.CompilerParams``); CPU test
images may carry an older one (``jax.experimental.shard_map`` with
``check_rep``/``auto``, ``pltpu.TPUCompilerParams``). Importing from
here keeps every kernel and parallel module loadable on both, instead
of each call site feature-testing jax inline.
"""
from __future__ import annotations

import contextlib

import jax

# compat context-mesh slot for jax builds without jax.sharding.set_mesh
# (set_mesh below stores the mesh here; get_context_mesh reads it)
_CTX_MESH = {"mesh": None}


def _native_ctx_mesh() -> bool:
    """ONE feature test for the whole context-mesh pair: jax must have
    BOTH jax.sharding.set_mesh and get_abstract_mesh for the native
    path — on builds with only one (the 0.5.x window shipped
    get_abstract_mesh before set_mesh went public), a split test would
    store the mesh in the compat slot while the probe reads the empty
    native abstract mesh, silently disabling manual sharding."""
    return (hasattr(jax.sharding, "set_mesh")
            and callable(getattr(jax.sharding, "get_abstract_mesh",
                                 None)))


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient sharding mesh:
    ``jax.sharding.set_mesh`` on new jax, else a module-level slot that
    ``get_context_mesh`` (the pallas-sharding probe) reads."""
    if _native_ctx_mesh():
        return jax.sharding.set_mesh(mesh)

    @contextlib.contextmanager
    def _cm():
        prev = _CTX_MESH["mesh"]
        _CTX_MESH["mesh"] = mesh
        try:
            yield mesh
        finally:
            _CTX_MESH["mesh"] = prev

    return _cm()


def get_context_mesh():
    """(mesh, eligible_axes) for manual shard_map over the ambient mesh.

    New jax: the abstract mesh + its AUTO axes (only those may go
    manual inside a pjit trace). Old jax (no abstract-mesh API): the
    compat ``set_mesh`` context, every axis eligible — 0.4.x has no
    auto/manual axis types, shard_map with a concrete mesh under jit
    is the normal form there."""
    if _native_ctx_mesh():
        amesh = jax.sharding.get_abstract_mesh()
        eligible = getattr(amesh, "auto_axes", ()) if amesh is not None \
            else ()
        return amesh, eligible
    mesh = _CTX_MESH["mesh"]
    return mesh, (mesh.axis_names if mesh is not None else ())


def make_mesh(shape, axis_names):
    """A 1-or-more-D device mesh over the first prod(shape) local
    devices: ``jax.make_mesh`` on jax builds that have it (it also
    picks a bandwidth-aware device order on real topologies), else the
    classic ``Mesh(np.reshape(devices), names)`` construction — the
    form every 0.4.x build accepts."""
    shape = tuple(int(s) for s in shape)
    if hasattr(jax, "make_mesh"):
        return jax.make_mesh(shape, tuple(axis_names))
    import math

    import numpy as np
    n = math.prod(shape)
    devs = jax.devices()
    if n > len(devs):
        raise ValueError(f"mesh {shape} needs {n} devices, have "
                         f"{len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]).reshape(shape),
                             tuple(axis_names))


def named_sharding(mesh, *names):
    """``NamedSharding(mesh, PartitionSpec(*names))`` in one call —
    the SNIPPETS-[3] utility shape. ``names`` entries are mesh axis
    names or None (replicated dim); no names at all = fully
    replicated over the mesh. One construction site so callers never
    touch the PartitionSpec class directly (its import path moved
    across jax versions; ``jax.sharding.PartitionSpec`` is the stable
    spelling both old and new builds expose)."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*names))


def device_put_sharded(tree, mesh, specs=None):
    """Place every leaf of ``tree`` on ``mesh`` under ``specs``:

    - ``specs=None``: every leaf replicated (the activation-staging
      case — a host batch must live on ALL mesh devices before a
      sharded-weight program can consume it without an implicit
      default-device transfer);
    - a single PartitionSpec-args tuple: every leaf gets it;
    - a dict keyed like ``tree`` (flat param dicts): per-leaf spec
      tuples, missing keys replicated.

    Unlike the LEGACY ``jax.device_put_sharded`` (per-device shard
    lists, removed on newer jax), this is the NamedSharding form that
    exists on both sides of the drift; the name is kept because it is
    the operation serving code means — "put this tree on the mesh,
    sharded as specified"."""
    def _sh(spec):
        return named_sharding(mesh, *spec) if spec else \
            named_sharding(mesh)

    if isinstance(tree, dict) and isinstance(specs, dict):
        unknown = set(specs) - set(tree)
        if unknown:
            # a spec naming no leaf is a silent replication bug in the
            # making (a renamed weight key would quietly lose its
            # sharding and bloat every device) — refuse loudly instead
            raise ValueError(f"device_put_sharded: spec keys "
                             f"{sorted(unknown)} name no tree leaf")
        return {k: jax.device_put(v, _sh(specs.get(k)))
                for k, v in tree.items()}
    sh = _sh(tuple(specs) if specs else ())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh),
                                  tree)


def tpu_compiler_params():
    """``pltpu.CompilerParams`` (new jax) or ``pltpu.TPUCompilerParams``
    (old name) — the Pallas kernel modules import this once instead of
    each feature-testing pltpu."""
    from jax.experimental.pallas import tpu as pltpu
    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False,
              axis_names=None):
    """``jax.shard_map`` when available, else the experimental one.

    ``axis_names`` (new-jax): the MANUAL axes. The old API takes the
    complement — ``auto`` = mesh axes left to GSPMD — so the set is
    inverted here. ``check_vma`` maps onto the old ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, **kw)
