"""Version-bridging wrappers for jax APIs that were renamed in flight.

The chip image carries a newer jax (``jax.shard_map`` with
``check_vma``/``axis_names``, ``pltpu.CompilerParams``); CPU test
images may carry an older one (``jax.experimental.shard_map`` with
``check_rep``/``auto``, ``pltpu.TPUCompilerParams``). Importing from
here keeps every kernel and parallel module loadable on both, instead
of each call site feature-testing jax inline.
"""
from __future__ import annotations

import contextlib

import jax

# compat context-mesh slot for jax builds without jax.sharding.set_mesh
# (set_mesh below stores the mesh here; get_context_mesh reads it)
_CTX_MESH = {"mesh": None}


def _native_ctx_mesh() -> bool:
    """ONE feature test for the whole context-mesh pair: jax must have
    BOTH jax.sharding.set_mesh and get_abstract_mesh for the native
    path — on builds with only one (the 0.5.x window shipped
    get_abstract_mesh before set_mesh went public), a split test would
    store the mesh in the compat slot while the probe reads the empty
    native abstract mesh, silently disabling manual sharding."""
    return (hasattr(jax.sharding, "set_mesh")
            and callable(getattr(jax.sharding, "get_abstract_mesh",
                                 None)))


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient sharding mesh:
    ``jax.sharding.set_mesh`` on new jax, else a module-level slot that
    ``get_context_mesh`` (the pallas-sharding probe) reads."""
    if _native_ctx_mesh():
        return jax.sharding.set_mesh(mesh)

    @contextlib.contextmanager
    def _cm():
        prev = _CTX_MESH["mesh"]
        _CTX_MESH["mesh"] = mesh
        try:
            yield mesh
        finally:
            _CTX_MESH["mesh"] = prev

    return _cm()


def get_context_mesh():
    """(mesh, eligible_axes) for manual shard_map over the ambient mesh.

    New jax: the abstract mesh + its AUTO axes (only those may go
    manual inside a pjit trace). Old jax (no abstract-mesh API): the
    compat ``set_mesh`` context, every axis eligible — 0.4.x has no
    auto/manual axis types, shard_map with a concrete mesh under jit
    is the normal form there."""
    if _native_ctx_mesh():
        amesh = jax.sharding.get_abstract_mesh()
        eligible = getattr(amesh, "auto_axes", ()) if amesh is not None \
            else ()
        return amesh, eligible
    mesh = _CTX_MESH["mesh"]
    return mesh, (mesh.axis_names if mesh is not None else ())


def tpu_compiler_params():
    """``pltpu.CompilerParams`` (new jax) or ``pltpu.TPUCompilerParams``
    (old name) — the Pallas kernel modules import this once instead of
    each feature-testing pltpu."""
    from jax.experimental.pallas import tpu as pltpu
    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False,
              axis_names=None):
    """``jax.shard_map`` when available, else the experimental one.

    ``axis_names`` (new-jax): the MANUAL axes. The old API takes the
    complement — ``auto`` = mesh axes left to GSPMD — so the set is
    inverted here. ``check_vma`` maps onto the old ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, **kw)
