"""Version-bridging wrappers for jax APIs that were renamed in flight.

The chip image carries a newer jax (``jax.shard_map`` with
``check_vma``/``axis_names``, ``pltpu.CompilerParams``); CPU test
images may carry an older one (``jax.experimental.shard_map`` with
``check_rep``/``auto``, ``pltpu.TPUCompilerParams``). Importing from
here keeps every kernel and parallel module loadable on both, instead
of each call site feature-testing jax inline.
"""
from __future__ import annotations

import jax


def tpu_compiler_params():
    """``pltpu.CompilerParams`` (new jax) or ``pltpu.TPUCompilerParams``
    (old name) — the Pallas kernel modules import this once instead of
    each feature-testing pltpu."""
    from jax.experimental.pallas import tpu as pltpu
    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False,
              axis_names=None):
    """``jax.shard_map`` when available, else the experimental one.

    ``axis_names`` (new-jax): the MANUAL axes. The old API takes the
    complement — ``auto`` = mesh axes left to GSPMD — so the set is
    inverted here. ``check_vma`` maps onto the old ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, **kw)
