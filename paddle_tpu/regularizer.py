"""paddle.regularizer (~ python/paddle/regularizer.py L1Decay/L2Decay over
fluid regularizer): weight decay terms consumed by Optimizer via
weight_decay= or per-param ParamAttr(regularizer=...)."""
from __future__ import annotations


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self.mode = "l1"

    def __call__(self, param):
        from .ops import math as M
        from .ops.reduction import sum as rsum
        return self.coeff * rsum(M.abs(param))


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self.mode = "l2"

    def __call__(self, param):
        from .ops.reduction import sum as rsum
        return 0.5 * self.coeff * rsum(param * param)
