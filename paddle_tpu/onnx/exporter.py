"""Static Program -> ONNX graph converter.

~ paddle2onnx (the backend behind reference python/paddle/onnx/export.py):
the reference maps ProgramDesc OpDescs to ONNX nodes; here the captured
static DAG (static/graph.py OpNode/StaticVar) is walked from the fetch
vars and each op is converted through OP_CONVERTERS. Parameters become
initializers; python attr args are recovered either from the op node's
args/kwargs (bound against the registered op signature) or from the
lowering closure's free variables (for functional wrappers that close
over their attrs).
"""
from __future__ import annotations

import inspect
from typing import Dict, List

import numpy as np

from ..core.tensor import Tensor
from ..static.graph import OpNode, StaticVar
from . import proto


class UnsupportedOp(ValueError):
    pass


def closure_attrs(fn) -> dict:
    """Free variables of a lowering closure, by name."""
    if fn.__closure__ is None:
        return {}
    return {name: cell.cell_contents
            for name, cell in zip(fn.__code__.co_freevars, fn.__closure__)}


def bound_attrs(node: OpNode) -> dict:
    """Bind node args/kwargs against the registered op signature to name
    the non-tensor attributes (matmul transpose flags, softmax axis, ...).
    """
    from ..ops.dispatch import OP_REGISTRY
    api = OP_REGISTRY.get(node.name)
    out = dict(node.kwargs)
    if api is None or not hasattr(api, "raw_fn"):
        return out
    try:
        sig = inspect.signature(api.raw_fn)
        ba = sig.bind_partial(*node.args)
        for k, v in ba.arguments.items():
            if not isinstance(v, (Tensor, StaticVar)):
                out.setdefault(k, v)
    except TypeError:
        pass
    return out


class ExportContext:
    def __init__(self, graph_name="main"):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.inputs: List[bytes] = []
        self.outputs: List[bytes] = []
        self.opset = 13
        self._names: Dict[int, str] = {}
        self._const_count = 0
        self.graph_name = graph_name

    def need_opset(self, v: int):
        self.opset = max(self.opset, v)

    # -- naming / constants ----------------------------------------------
    def name_of(self, var) -> str:
        if isinstance(var, StaticVar):
            return var.name
        key = id(var)
        if key not in self._names:
            nm = f"const_{self._const_count}"
            self._const_count += 1
            self._names[key] = nm
            arr = np.asarray(var._value if isinstance(var, Tensor) else var)
            self.initializers.append(proto.tensor_proto(nm, arr))
        return self._names[key]

    def add_const(self, arr: np.ndarray, hint="c") -> str:
        nm = f"{hint}_{self._const_count}"
        self._const_count += 1
        self.initializers.append(proto.tensor_proto(nm, np.asarray(arr)))
        return nm

    def emit(self, op_type, inputs, outputs, attrs=None, name=""):
        self.nodes.append(proto.node_proto(
            op_type, inputs, outputs, name=name, attrs=attrs))


# ---------------------------------------------------------------------------
# converters: fn(ctx, node, ins, outs, attrs)
#   ins  = ONNX names of the node's *tensor* inputs, in arg order
#   outs = ONNX names of the node's outputs
# ---------------------------------------------------------------------------
def _simple(onnx_op, min_opset=13):
    def conv(ctx, node, ins, outs, attrs):
        ctx.need_opset(min_opset)
        ctx.emit(onnx_op, ins, outs)
    return conv


def _swap_last2_perm(var):
    nd = len(var._shape if isinstance(var, StaticVar)
             else var._value.shape)
    perm = list(range(nd))
    if nd >= 2:
        perm[-1], perm[-2] = perm[-2], perm[-1]
    return perm


def _conv_matmul(ctx, node, ins, outs, attrs):
    x, y = ins
    tensors = [a for a in node.args if isinstance(a, (Tensor, StaticVar))]
    if attrs.get("transpose_x"):
        x2 = outs[0] + "_xT"
        ctx.emit("Transpose", [x], [x2],
                 {"perm": _swap_last2_perm(tensors[0])})
        x = x2
    if attrs.get("transpose_y"):
        y2 = outs[0] + "_yT"
        ctx.emit("Transpose", [y], [y2],
                 {"perm": _swap_last2_perm(tensors[1])})
        y = y2
    ctx.emit("MatMul", [x, y], outs)


def _conv_linear(ctx, node, ins, outs, attrs):
    if len(ins) == 3:
        mm = outs[0] + "_mm"
        ctx.emit("MatMul", ins[:2], [mm])
        ctx.emit("Add", [mm, ins[2]], outs)
    else:
        ctx.emit("MatMul", ins, outs)


def _conv_softmax(ctx, node, ins, outs, attrs):
    ctx.emit("Softmax", ins, outs, {"axis": int(attrs.get("axis", -1))})


def _conv_gelu(ctx, node, ins, outs, attrs):
    ctx.need_opset(20)
    approx = "tanh" if attrs.get("approximate") else "none"
    ctx.emit("Gelu", ins, outs, {"approximate": approx})


def _conv_reshape(ctx, node, ins, outs, attrs):
    shape = [int(d) for d in node.out_vars[0]._shape]
    shp = ctx.add_const(np.asarray(shape, np.int64), "shape")
    ctx.emit("Reshape", [ins[0], shp], outs)


_conv_flatten = _conv_reshape  # static shapes: both are a Reshape


def _conv_transpose(ctx, node, ins, outs, attrs):
    perm = attrs.get("perm")
    a = {} if perm is None else {"perm": [int(p) for p in perm]}
    ctx.emit("Transpose", ins, outs, a)


def _conv_concat(ctx, node, ins, outs, attrs):
    cl = closure_attrs(node.fn)
    ctx.emit("Concat", ins, outs, {"axis": int(cl.get("axis", 0))})


def _pads_of(padding, n):
    if isinstance(padding, str):
        raise UnsupportedOp(f"string padding {padding!r} in ONNX export")
    if isinstance(padding, int):
        per = [padding] * n
    else:
        per = [int(p) for p in padding]
        if len(per) == 1:
            per = per * n
    return per + per  # onnx wants begins then ends


def _tuplize(v, n):
    if isinstance(v, int):
        return [v] * n
    return [int(t) for t in v]


def _conv_conv2d(ctx, node, ins, outs, attrs):
    cl = closure_attrs(node.fn)
    if cl.get("data_format", "NCHW") != "NCHW":
        raise UnsupportedOp("ONNX Conv requires NCHW")
    a = {"strides": _tuplize(cl.get("stride", 1), 2),
         "pads": _pads_of(cl.get("padding", 0), 2),
         "dilations": _tuplize(cl.get("dilation", 1), 2),
         "group": int(cl.get("groups", 1))}
    ctx.emit("Conv", ins, outs, a)


def _conv_pool2d(onnx_op):
    def conv(ctx, node, ins, outs, attrs):
        cl = closure_attrs(node.fn)
        if cl.get("data_format", "NCHW") != "NCHW":
            raise UnsupportedOp(f"ONNX {onnx_op} requires NCHW")
        if cl.get("return_mask"):
            raise UnsupportedOp("return_mask pooling in ONNX export")
        ks = _tuplize(cl["kernel_size"], 2)
        stride = cl.get("stride")
        a = {"kernel_shape": ks,
             "strides": _tuplize(stride if stride is not None
                                 else cl["kernel_size"], 2),
             "pads": _pads_of(cl.get("padding", 0), 2)}
        if cl.get("ceil_mode"):
            a["ceil_mode"] = 1
        ctx.emit(onnx_op, ins, outs, a)
    return conv


def _conv_batch_norm(ctx, node, ins, outs, attrs):
    cl = closure_attrs(node.fn)
    x, mean, var = ins[0], ins[1], ins[2]
    rest = ins[3:]
    c = int(node.out_vars[0]._shape[1])
    i = 0
    if cl.get("has_w"):
        scale = rest[i]
        i += 1
    else:
        scale = ctx.add_const(np.ones(c, np.float32), "bn_scale")
    b = rest[i] if cl.get("has_b") else ctx.add_const(
        np.zeros(c, np.float32), "bn_bias")
    eps = float(cl.get("epsilon", 1e-5))
    ctx.emit("BatchNormalization", [x, scale, b, mean, var], outs,
             {"epsilon": eps})


def _conv_layer_norm(ctx, node, ins, outs, attrs):
    ctx.need_opset(17)
    cl = closure_attrs(node.fn)
    axes = cl.get("axes", (-1,))
    a = {"axis": int(axes[0]), "epsilon": float(cl.get("epsilon", 1e-5))}
    if not cl.get("has_w") or not cl.get("has_b"):
        raise UnsupportedOp("LayerNormalization export needs weight+bias")
    ctx.emit("LayerNormalization", ins, outs, a)


def _conv_reduce(onnx_op, axes_as_input=False):
    def conv(ctx, node, ins, outs, attrs):
        axis = attrs.get("axis")
        keep = 1 if attrs.get("keepdim") else 0
        axes = None if axis is None else (
            [int(a) for a in axis] if isinstance(axis, (list, tuple))
            else [int(axis)])
        if axes_as_input:  # ReduceSum >= opset 13 takes axes as an input
            inputs = list(ins)
            if axes is not None:
                inputs.append(ctx.add_const(np.asarray(axes, np.int64),
                                            "axes"))
            ctx.emit(onnx_op, inputs, outs, {"keepdims": keep})
        else:
            a = {"keepdims": keep}
            if axes is not None:
                a["axes"] = axes
            ctx.emit(onnx_op, ins, outs, a)
    return conv


def _conv_embedding(ctx, node, ins, outs, attrs):
    # embedding(ids, weight) -> Gather(weight, ids)
    ctx.emit("Gather", [ins[1], ins[0]], outs, {"axis": 0})


OP_CONVERTERS = {
    "matmul": _conv_matmul,
    "mm": _simple("MatMul"),
    "linear": _conv_linear,
    "add": _simple("Add"),
    "subtract": _simple("Sub"),
    "multiply": _simple("Mul"),
    "divide": _simple("Div"),
    "pow": _simple("Pow"),
    "maximum": _simple("Max"),
    "minimum": _simple("Min"),
    "relu": _simple("Relu"),
    "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"),
    "exp": _simple("Exp"),
    "log": _simple("Log"),
    "sqrt": _simple("Sqrt"),
    "abs": _simple("Abs"),
    "neg": _simple("Neg"),
    "erf": _simple("Erf"),
    "floor": _simple("Floor"),
    "ceil": _simple("Ceil"),
    "gelu": _conv_gelu,
    "softmax": _conv_softmax,
    "reshape": _conv_reshape,
    "flatten": _conv_flatten,
    "transpose": _conv_transpose,
    "concat": _conv_concat,
    "conv2d": _conv_conv2d,
    "max_pool2d": _conv_pool2d("MaxPool"),
    "avg_pool2d": _conv_pool2d("AveragePool"),
    "batch_norm": _conv_batch_norm,
    "layer_norm": _conv_layer_norm,
    "mean": _conv_reduce("ReduceMean"),
    "sum": _conv_reduce("ReduceSum", axes_as_input=True),
    "max": _conv_reduce("ReduceMax"),
    "min": _conv_reduce("ReduceMin"),
    "embedding": _conv_embedding,
}


def _topo_order(outputs) -> List[OpNode]:
    seen = set()
    order: List[OpNode] = []

    def visit(node: OpNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        for a in node.args:
            if isinstance(a, StaticVar) and a._node is not None:
                visit(a._node)
        order.append(node)

    for v in outputs:
        if isinstance(v, StaticVar) and v._node is not None:
            visit(v._node)
    return order


def program_to_onnx(feed_vars, fetch_vars, graph_name="main") -> bytes:
    """Convert the DAG reaching `fetch_vars` into ONNX ModelProto bytes.

    feed_vars: list of StaticVar graph inputs (static.data).
    fetch_vars: list of StaticVar outputs.
    """
    ctx = ExportContext(graph_name)
    for v in feed_vars:
        ctx.inputs.append(proto.value_info_proto(
            v.name, v._shape, np.dtype(v._jdtype)))
    for node in _topo_order(fetch_vars):
        conv = OP_CONVERTERS.get(node.name)
        if conv is None:
            raise UnsupportedOp(
                f"op '{node.name}' has no ONNX converter "
                f"(supported: {sorted(OP_CONVERTERS)})")
        ins = [ctx.name_of(a) for a in node.args
               if isinstance(a, (Tensor, StaticVar))]
        outs = [ov.name for ov in node.out_vars]
        conv(ctx, node, ins, outs, bound_attrs(node))
    for v in fetch_vars:
        ctx.outputs.append(proto.value_info_proto(
            v.name, v._shape, np.dtype(v._jdtype)))
    graph = proto.graph_proto(ctx.graph_name, ctx.nodes, ctx.inputs,
                              ctx.outputs, ctx.initializers)
    return proto.model_proto(graph, opset=ctx.opset)
