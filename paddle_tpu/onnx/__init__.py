"""paddle.onnx — deployment export slot.

~ python/paddle/onnx/export.py (paddle2onnx bridge). This framework's
deployment artifact is the serialized StableHLO executable
(jax.export — see jit.save / inference.Predictor), which is the
TPU-serving equivalent of an ONNX graph. ``export`` writes that artifact;
when the optional ``onnx`` package is installed it additionally emits a
true ONNX model via the jax->onnx route if available.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Write <path>.onnx when onnx tooling exists, else the StableHLO
    artifact set (same deployment contract, TPU-native container)."""
    from .. import jit
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False
    jit.save(layer, path, input_spec=input_spec)
    if not have_onnx:
        import warnings
        warnings.warn(
            "onnx is not installed; exported StableHLO artifacts "
            f"({path}.pdexport) instead — the TPU-serving deployment format")
    return path
