"""paddle.onnx — ONNX model export.

~ python/paddle/onnx/export.py (paddle2onnx bridge). The converter lives
in-tree (exporter.py maps the captured static DAG to ONNX nodes;
proto.py writes the protobuf wire format directly, so no `onnx` package
is required). Ops without a converter fall back to the StableHLO artifact
set (jit.save) — the TPU-serving deployment format.
"""
from __future__ import annotations

from . import proto  # noqa: F401
from .exporter import (OP_CONVERTERS, UnsupportedOp,  # noqa: F401
                       program_to_onnx)


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Write <path>.onnx (real ONNX protobuf). Requires input_spec.

    The layer's forward is re-traced in static-capture mode so every op
    lands in the DAG the converter understands; ops with no ONNX mapping
    raise UnsupportedOp unless ``fallback_stablehlo=True`` (default), in
    which case the StableHLO artifact set is written instead.
    """
    from .. import jit
    from ..jit import InputSpec
    from ..static import graph as _sg

    fallback = configs.pop("fallback_stablehlo", True)
    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    specs = [s if isinstance(s, InputSpec) else InputSpec(s)
             for s in input_spec]

    main, startup = _sg.Program(), _sg.Program()
    was_static = _sg.in_static_mode()
    try:
        _sg.enable_static()
        with _sg.program_guard(main, startup):
            feeds = [_sg.data(f"x{i}", s.shape, dtype=s.dtype)
                     for i, s in enumerate(specs)]
            out = layer(*feeds) if callable(layer) else layer.forward(*feeds)
        fetches = list(out) if isinstance(out, (tuple, list)) else [out]
        blob = program_to_onnx(feeds, fetches,
                               graph_name=type(layer).__name__)
    except UnsupportedOp:
        if not fallback:
            raise
        import warnings
        warnings.warn(
            "model contains ops without ONNX converters; wrote StableHLO "
            f"artifacts ({path}.pdexport) instead — the TPU-serving format")
        jit.save(layer, path, input_spec=input_spec)
        return path
    finally:
        if not was_static:
            _sg.disable_static()

    target = path if path.endswith(".onnx") else path + ".onnx"
    with open(target, "wb") as f:
        f.write(blob)
    return target
