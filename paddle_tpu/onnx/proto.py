"""Minimal protobuf wire-format writer + ONNX message builders.

The environment has no `onnx` package, but ONNX files are plain protobuf
and the message schema (onnx/onnx.proto) is stable/public — so paddle2onnx
capability (reference python/paddle/onnx/export.py) is implemented by
emitting the wire format directly. Field numbers below follow onnx.proto
(IR version 8 / opset 17 era). A generic reader (`decode_message`) parses
any protobuf back into {field_number: [values]} for tests and tooling.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

import numpy as np

# -- wire primitives --------------------------------------------------------

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's complement, 10-byte encoding
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wt: int) -> bytes:
    return _varint((field << 3) | wt)


def emit_varint(field: int, value: int) -> bytes:
    return _key(field, _WT_VARINT) + _varint(int(value))


def emit_bytes(field: int, blob: bytes) -> bytes:
    return _key(field, _WT_LEN) + _varint(len(blob)) + blob


def emit_string(field: int, s: str) -> bytes:
    return emit_bytes(field, s.encode("utf-8"))


def emit_float(field: int, v: float) -> bytes:
    return _key(field, _WT_I32) + struct.pack("<f", float(v))


# -- generic decoder (for tests) -------------------------------------------

Value = Union[int, bytes]


def decode_message(blob: bytes) -> Dict[int, List[Value]]:
    """Parse one protobuf message into {field: [raw values]}; length-
    delimited fields come back as bytes (decode nested messages by calling
    again)."""
    out: Dict[int, List[Value]] = {}
    i = 0
    n = len(blob)
    while i < n:
        tag, i = _read_varint(blob, i)
        field, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            v, i = _read_varint(blob, i)
        elif wt == _WT_LEN:
            ln, i = _read_varint(blob, i)
            v = blob[i:i + ln]
            i += ln
        elif wt == _WT_I32:
            v = struct.unpack("<f", blob[i:i + 4])[0]
            i += 4
        elif wt == _WT_I64:
            v = struct.unpack("<d", blob[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"bad wire type {wt}")
        out.setdefault(field, []).append(v)
    return out


def _read_varint(blob: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    v = 0
    while True:
        b = blob[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


# -- ONNX schema constants --------------------------------------------------

class DataType:
    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    INT32 = 6
    INT64 = 7
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    BFLOAT16 = 16


_NP_TO_ONNX = {
    np.dtype(np.float32): DataType.FLOAT,
    np.dtype(np.float64): DataType.DOUBLE,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(bool): DataType.BOOL,
}


def onnx_dtype(np_dtype) -> int:
    d = np.dtype(np_dtype)
    if d.name == "bfloat16":
        return DataType.BFLOAT16
    try:
        return _NP_TO_ONNX[d]
    except KeyError:
        raise ValueError(f"dtype {d} has no ONNX mapping") from None


class AttrType:
    FLOAT = 1
    INT = 2
    STRING = 3
    TENSOR = 4
    FLOATS = 6
    INTS = 7
    STRINGS = 8


# -- ONNX message builders --------------------------------------------------

def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    msg = b""
    for d in arr.shape:
        msg += emit_varint(1, d)
    msg += emit_varint(2, onnx_dtype(arr.dtype))
    msg += emit_string(8, name)
    if arr.dtype.name == "bfloat16":
        raw = arr.view(np.uint16).tobytes()
    else:
        raw = arr.tobytes()
    msg += emit_bytes(9, raw)
    return msg


def attribute_proto(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    type=20."""
    msg = emit_string(1, name)
    if isinstance(value, bool):
        msg += emit_varint(3, int(value)) + emit_varint(20, AttrType.INT)
    elif isinstance(value, int):
        msg += emit_varint(3, value) + emit_varint(20, AttrType.INT)
    elif isinstance(value, float):
        msg += emit_float(2, value) + emit_varint(20, AttrType.FLOAT)
    elif isinstance(value, str):
        msg += emit_string(4, value) + emit_varint(20, AttrType.STRING)
    elif isinstance(value, np.ndarray):
        msg += emit_bytes(5, tensor_proto(name + "_t", value))
        msg += emit_varint(20, AttrType.TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, bool, np.integer)) for v in value):
            for v in value:
                msg += emit_varint(8, int(v))
            msg += emit_varint(20, AttrType.INTS)
        elif all(isinstance(v, (int, float, np.floating)) for v in value):
            for v in value:
                msg += emit_float(7, float(v))
            msg += emit_varint(20, AttrType.FLOATS)
        else:
            raise ValueError(f"unsupported attr list {value!r}")
    else:
        raise ValueError(f"unsupported attr {value!r}")
    return msg


def node_proto(op_type: str, inputs, outputs, name: str = "",
               attrs: dict | None = None) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    msg = b""
    for s in inputs:
        msg += emit_string(1, s)
    for s in outputs:
        msg += emit_string(2, s)
    if name:
        msg += emit_string(3, name)
    msg += emit_string(4, op_type)
    for k, v in (attrs or {}).items():
        msg += emit_bytes(5, attribute_proto(k, v))
    return msg


def _tensor_shape_proto(shape) -> bytes:
    """TensorShapeProto: dim=1 (Dimension: dim_value=1, dim_param=2)."""
    msg = b""
    for i, d in enumerate(shape):
        if d is None or int(d) < 0:
            dim = emit_string(2, f"dyn_{i}")
        else:
            dim = emit_varint(1, int(d))
        msg += emit_bytes(1, dim)
    return msg


def value_info_proto(name: str, shape, np_dtype) -> bytes:
    """ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1
    (elem_type=1, shape=2)."""
    tensor_type = emit_varint(1, onnx_dtype(np_dtype))
    tensor_type += emit_bytes(2, _tensor_shape_proto(shape))
    type_proto = emit_bytes(1, tensor_type)
    return emit_string(1, name) + emit_bytes(2, type_proto)


def graph_proto(name: str, nodes, inputs, outputs, initializers) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    msg = b""
    for n in nodes:
        msg += emit_bytes(1, n)
    msg += emit_string(2, name)
    for t in initializers:
        msg += emit_bytes(5, t)
    for v in inputs:
        msg += emit_bytes(11, v)
    for v in outputs:
        msg += emit_bytes(12, v)
    return msg


def model_proto(graph: bytes, opset: int = 17,
                producer: str = "paddle-tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8
    (OperatorSetIdProto: domain=1, version=2)."""
    msg = emit_varint(1, 8)  # IR version 8
    msg += emit_string(2, producer)
    msg += emit_bytes(7, graph)
    msg += emit_bytes(8, emit_string(1, "") + emit_varint(2, opset))
    return msg
