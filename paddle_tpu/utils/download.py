"""Weight path helper (zero-egress: local cache only).

~ python/paddle/utils/download.py get_weights_path_from_url — in this
environment there is no network; the helper resolves URLs to a local cache
and errors with a clear message if the file was never placed there.
"""
from __future__ import annotations

import os

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    fname = url.split("/")[-1]
    path = os.path.join(WEIGHTS_HOME, fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"pretrained weights {fname} not found at {path}; this "
            "environment has no network egress — place the file there "
            "manually")
    return path
