"""ctypes bindings for the native runtime (csrc/).

Loads libpaddle_tpu_native.so (built by csrc/Makefile — attempted
automatically on first import). All users fall back to pure-python when the
library is unavailable, so the wheel works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "lib",
                         "libpaddle_tpu_native.so")
_lib = None
_tried = False


def _build() -> bool:
    csrc = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
    if not os.path.isdir(csrc):
        return False
    try:
        subprocess.run(["make", "-s"], cwd=csrc, check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    path = os.path.abspath(_LIB_PATH)
    if not os.path.exists(path):
        if not _build():
            return None
    if not os.path.exists(path):
        return None
    lib = _load_and_bind(path)
    if lib is None and _build():
        # a stale prebuilt .so missing newer symbols: rebuild, then load
        # via a fresh temp path — re-dlopening the SAME path returns the
        # already-mapped stale image from the loader cache
        import atexit
        import shutil
        import tempfile
        try:
            tmp = tempfile.NamedTemporaryFile(
                suffix=".so", delete=False)
            tmp.close()
            shutil.copy(path, tmp.name)
            lib = _load_and_bind(tmp.name)
            # the mapping survives unlink on Linux; don't litter /tmp
            atexit.register(lambda p=tmp.name: os.path.exists(p)
                            and os.unlink(p))
        except OSError:
            lib = None
    _lib = lib
    return _lib


def _load_and_bind(path: str) -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    try:
        _bind_signatures(lib)
    except AttributeError:
        # missing symbol (stale build) — caller may rebuild; contract is
        # "None means pure-python fallback", never an exception
        return None
    return lib


def _bind_signatures(lib: ctypes.CDLL) -> None:
    # signatures
    lib.tcpstore_server_start.restype = ctypes.c_void_p
    lib.tcpstore_server_start.argtypes = [ctypes.c_int]
    lib.tcpstore_connect.restype = ctypes.c_int
    lib.tcpstore_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_add.restype = ctypes.c_int64
    lib.tcpstore_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.tcpstore_wait.restype = ctypes.c_int
    lib.tcpstore_wait.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_delete.restype = ctypes.c_int
    lib.tcpstore_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.tcpstore_close.argtypes = [ctypes.c_int]

    lib.bl_create.restype = ctypes.c_void_p
    lib.bl_create.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                              ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
                              ctypes.c_int]
    lib.bl_submit.restype = ctypes.c_int64
    lib.bl_submit.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_int64]
    lib.bl_next.restype = ctypes.c_int64
    lib.bl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.bl_destroy.argtypes = [ctypes.c_void_p]
    lib.shm_ring_create.restype = ctypes.c_void_p
    lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64]
    lib.shm_ring_open.restype = ctypes.c_void_p
    lib.shm_ring_open.argtypes = [ctypes.c_char_p]
    lib.shm_ring_slot_size.restype = ctypes.c_int64
    lib.shm_ring_slot_size.argtypes = [ctypes.c_void_p]
    lib.shm_ring_write.restype = ctypes.c_int64
    lib.shm_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int64]
    lib.shm_ring_read.restype = ctypes.c_int64
    lib.shm_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_int64, ctypes.c_int64]
    lib.shm_ring_close.argtypes = [ctypes.c_void_p]

    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.wp_new.restype = ctypes.c_void_p
    lib.wp_new.argtypes = [ctypes.c_char_p, i32p, ctypes.c_int32]
    lib.wp_free.argtypes = [ctypes.c_void_p]
    lib.wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i32p,
                              ctypes.c_int32, ctypes.c_int32,
                              ctypes.c_int32, ctypes.c_int32, i32p, i32p,
                              ctypes.c_int32]


def available() -> bool:
    return get_lib() is not None
