"""Custom C++ op toolchain.

~ python/paddle/utils/cpp_extension/ (CppExtension, load — JIT-builds user
C++ against the installed headers) paired with the C++ custom-op ABI
(paddle/phi/api/ext/op_meta_info.h, framework/custom_operator.cc).

TPU-native shape: a custom op is a C function  f(const T** ins, T* out, ...)
compiled to a shared lib; it executes on host via jax.pure_callback (XLA
custom-call-to-host), composing with jit. Device-side custom kernels are
written in Pallas instead (ops/pallas/) — that is the CUDA-kernel slot.
No pybind11 needed: ctypes + numpy buffers.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

_CACHE_DIR = os.path.expanduser("~/.cache/paddle_tpu/extensions")


def load(name: str, sources: Sequence[str], extra_cxx_cflags=(),
         build_directory: str | None = None, verbose: bool = False):
    """JIT-compile C++ sources into a shared lib, return ctypes handle.

    ~ cpp_extension.load(): uses g++ directly (no setuptools round trip).
    """
    build_dir = build_directory or _CACHE_DIR
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    need = (not os.path.exists(out)
            or any(os.path.getmtime(s) > os.path.getmtime(out)
                   for s in srcs))
    if need:
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *extra_cxx_cflags, "-o", out, *srcs]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


class CustomOp:
    """Wraps a C symbol into a framework op running via pure_callback.

    The C signature contract (all f32, row-major):
        void op(const float** inputs, const long long** shapes,
                const int* ndims, int n_inputs, float* output)
    with the output buffer sized by ``out_shape_fn``.
    """

    def __init__(self, lib: ctypes.CDLL, symbol: str,
                 out_shape_fn: Callable[..., Sequence[int]],
                 out_dtype=np.float32):
        self.fn = getattr(lib, symbol)
        self.fn.restype = None
        self.out_shape_fn = out_shape_fn
        self.out_dtype = np.dtype(out_dtype)
        self.symbol = symbol

    def _host_call(self, *arrays):
        arrays = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
        out_shape = tuple(self.out_shape_fn(*[a.shape for a in arrays]))
        out = np.zeros(out_shape, dtype=self.out_dtype)
        n = len(arrays)
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        shapes = [np.asarray(a.shape, dtype=np.int64) for a in arrays]
        shape_ptrs = (ctypes.POINTER(ctypes.c_longlong) * n)(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
              for s in shapes])
        ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        self.fn(in_ptrs, shape_ptrs, ndims, ctypes.c_int(n),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out

    def __call__(self, *tensors):
        def jfn(*vals):
            out_shape = tuple(self.out_shape_fn(
                *[tuple(v.shape) for v in vals]))
            return jax.pure_callback(
                self._host_call,
                jax.ShapeDtypeStruct(out_shape, self.out_dtype), *vals)
        return apply_op(f"custom::{self.symbol}", jfn, *tensors,
                        nondiff=True)


class CppExtension:
    """setuptools-style descriptor (~ CppExtension) for API parity."""

    def __init__(self, sources, name=None, **kw):
        self.sources = list(sources)
        self.name = name or "custom_ext"

    def build(self, build_directory=None):
        return load(self.name, self.sources,
                    build_directory=build_directory)
