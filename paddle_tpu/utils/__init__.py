from . import native  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """~ paddle.utils.deprecated decorator (python/paddle/utils/deprecated.py)."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f". Reason: {reason}"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def try_import(module_name, err_msg=None):
    """~ paddle.utils.try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"Failed to import {module_name}. Please install "
                          f"it before using this API.")


def require_version(min_version, max_version=None):
    """~ paddle.utils.require_version — checks the framework version."""
    from .. import __version__

    def to_tuple(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())
    cur = to_tuple(__version__)
    if to_tuple(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and to_tuple(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


def run_check():
    """~ paddle.utils.run_check — verifies the runtime can compile and run a
    matmul on the available device(s)."""
    import jax
    import jax.numpy as jnp
    n = len(jax.devices())
    x = jnp.ones((8, 8))
    y = jax.jit(lambda a: a @ a)(x)
    assert float(y[0, 0]) == 8.0
    print(f"paddle_tpu is installed successfully! "
          f"{n} {jax.devices()[0].platform} device(s) available.")
