from . import native  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
