"""Profiler.

~ python/paddle/profiler/ (profiler.py:270 scheduler-driven Profiler,
RecordEvent span API platform/profiler/event_tracing.h:47). TPU-native
backing: jax.profiler (XPlane) for device traces + a host-side span
recorder exported as chrome://tracing JSON (~ ChromeTracingLogger,
platform/profiler/chrometracing_logger.h:28).
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from enum import Enum
from typing import Callable, Iterable, Optional

import jax


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1  # accel alias
    TPU = 1


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """~ profiler.py make_scheduler:140."""
    period = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return fn


def export_chrome_tracing(dir_name: str, worker_name: str | None = None,
                          *, timestamp: bool = True):
    """Trace-ready handler writing chrome JSON under ``dir_name``.

    ``timestamp=True`` (default) keeps the historical wall-stamped
    suffix so repeated runs never clobber each other;
    ``timestamp=False`` writes exactly ``<worker_name>.json`` — the
    deterministic name tests and diffable artifacts need."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        fname = f"{name}_{int(time.time())}.json" if timestamp \
            else f"{name}.json"
        prof._export_chrome(os.path.join(dir_name, fname))
    return handler


class _SpanStore:
    """Span sink. Hot path (add) goes through the native ring collector
    (csrc/span_collector.cc — atomic slot claim, interned names, no
    allocation; ~ the reference's HostTracer/host_event_recorder ring) when
    libpaddle_tpu_native is built; pure-python list otherwise."""

    def __init__(self, capacity=1 << 16):
        self.lock = threading.Lock()
        self.events = None          # python fallback storage
        self.enabled = False
        self._native = None
        self._ids = {}
        try:
            import ctypes
            from ..utils import native as _nat
            lib = _nat.get_lib()
            if lib is not None and hasattr(lib, "spans_create"):
                lib.spans_create.restype = ctypes.c_void_p
                lib.spans_create.argtypes = [ctypes.c_uint64]
                lib.spans_destroy.argtypes = [ctypes.c_void_p]
                lib.spans_intern.restype = ctypes.c_int32
                lib.spans_intern.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p]
                lib.spans_add.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                          ctypes.c_double, ctypes.c_double,
                                          ctypes.c_uint64]
                lib.spans_count.restype = ctypes.c_uint64
                lib.spans_count.argtypes = [ctypes.c_void_p]
                lib.spans_dump.restype = ctypes.c_uint64
                lib.spans_name.restype = ctypes.c_uint64
                lib.spans_reset.argtypes = [ctypes.c_void_p]
                self._lib = lib
                self._native = ctypes.c_void_p(lib.spans_create(capacity))
                self._capacity = capacity
        except Exception:
            self._native = None
        if self._native is None:
            self.events = []

    def add(self, name, ts, dur, tid):
        if not self.enabled:
            return
        if self._native is not None:
            nid = self._ids.get(name)
            if nid is None:
                nid = self._lib.spans_intern(self._native, name.encode())
                self._ids[name] = nid
            self._lib.spans_add(self._native, nid, ts, dur, tid & ((1 << 63) - 1))
            return
        with self.lock:
            self.events.append({"name": name, "ph": "X", "pid": os.getpid(),
                                "tid": tid, "ts": ts * 1e6, "dur": dur * 1e6})

    def drain(self):
        """Chrome-trace event dicts for everything recorded so far."""
        if self._native is None:
            with self.lock:
                return list(self.events)
        import ctypes
        import numpy as np
        n = int(self._lib.spans_count(self._native))
        if n == 0:
            return []
        name_ids = (ctypes.c_int32 * n)()
        t0s = (ctypes.c_double * n)()
        durs = (ctypes.c_double * n)()
        tids = (ctypes.c_uint64 * n)()
        got = int(self._lib.spans_dump(self._native, name_ids, t0s, durs,
                                       tids, n))
        id_to_name = {v: k for k, v in self._ids.items()}
        pid = os.getpid()
        return [{"name": id_to_name.get(name_ids[i], f"id{name_ids[i]}"),
                 "ph": "X", "pid": pid, "tid": int(tids[i]),
                 "ts": t0s[i] * 1e6, "dur": durs[i] * 1e6}
                for i in range(got)]

    def clear(self):
        if self._native is not None:
            self._lib.spans_reset(self._native)
        elif self.events is not None:
            with self.lock:
                self.events.clear()


_spans = _SpanStore()


class RecordEvent:
    """~ platform/profiler/event_tracing.h RecordEvent — host span marker."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()

    def end(self):
        if self._t0 is not None:
            _spans.add(self.name, self._t0, time.perf_counter() - self._t0,
                       threading.get_ident())
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """~ paddle.profiler.Profiler (profiler.py:270)."""

    def __init__(self, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.scheduler = scheduler if callable(scheduler) else (
            make_scheduler(0, 0, scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self.on_trace_ready = on_trace_ready
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._jax_active = False
        self._logdir = os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                      "/tmp/paddle_tpu_profile")
        self.timer_only = timer_only
        self._step_times = []
        self._last_step_t = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.state = (self.scheduler(self.step_num) if self.scheduler
                      else ProfilerState.RECORD)
        self._maybe_transition(ProfilerState.CLOSED, self.state)
        self._last_step_t = time.perf_counter()
        return self

    def stop(self):
        if self._jax_active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_active = False
        _spans.enabled = False
        if self.on_trace_ready:
            self.on_trace_ready(self)
        self.state = ProfilerState.CLOSED

    def _maybe_transition(self, old, new):
        starting = new in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN) and \
            old in (ProfilerState.CLOSED, ProfilerState.READY)
        stopping = old in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN) and \
            new in (ProfilerState.CLOSED, ProfilerState.READY)
        if starting and not self.timer_only:
            _spans.enabled = True
            if not self._jax_active:
                try:
                    jax.profiler.start_trace(self._logdir)
                    self._jax_active = True
                except Exception:
                    pass
        if stopping:
            _spans.enabled = False
            if self._jax_active:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._jax_active = False
            if self.on_trace_ready and old == ProfilerState.RECORD_AND_RETURN:
                self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t, num_samples))
        self._last_step_t = now
        self.step_num += 1
        if self.scheduler:
            new = self.scheduler(self.step_num)
            self._maybe_transition(self.state, new)
            self.state = new

    def step_info(self, unit: str = "samples"):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.array([t for t, _ in self._step_times[-20:]])
        ips = ""
        ns = [n for _, n in self._step_times[-20:] if n]
        if ns:
            ips = f", ips {np.mean(ns) / np.mean(ts):.2f} {unit}/s"
        return (f"avg step {ts.mean()*1000:.2f} ms, min {ts.min()*1000:.2f}, "
                f"max {ts.max()*1000:.2f}{ips}")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export -------------------------------------------------------------
    def _export_chrome(self, path):
        events = _spans.drain()
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def export(self, path: str, format: str = "json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """~ python/paddle/profiler/profiler_statistic.py summary tables:
        per-op calls/total/avg/max/ratio sorted by total time."""
        events = _spans.drain()
        agg = {}
        for e in events:
            name = e["name"]
            a = agg.setdefault(name, [0, 0.0, 0.0])
            dur = e["dur"] / 1000.0
            a[0] += 1
            a[1] += dur
            a[2] = max(a[2], dur)
        grand = sum(a[1] for a in agg.values()) or 1.0
        lines = [f"{'name':40s} {'calls':>8s} {'total_ms':>12s} "
                 f"{'avg_ms':>10s} {'max_ms':>10s} {'ratio':>7s}"]
        for name, (calls, total, mx) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"{name[:40]:40s} {calls:8d} {total:12.3f} "
                f"{total / calls:10.3f} {mx:10.3f} {total / grand:6.1%}")
        return "\n".join(lines)


@contextmanager
def profile(*args, **kwargs):
    p = Profiler(*args, **kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


class SortedKeys(Enum):
    """~ paddle.profiler.SortedKeys — summary table sort orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name: str, worker_name: str | None = None):
    """~ paddle.profiler.export_protobuf — binary trace dump handler (the
    pb role is played by a pickled event list; chrome JSON is the
    interoperable format)."""
    def handler(prof: "Profiler"):
        import pickle
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{int(time.time())}.pb")
        with open(path, "wb") as f:
            pickle.dump(_spans.drain(), f, protocol=4)
    return handler


def load_profiler_result(filename: str):
    """~ paddle.profiler.load_profiler_result."""
    import pickle
    with open(filename, "rb") as f:
        return pickle.load(f)
