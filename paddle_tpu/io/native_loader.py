"""Native batch assembly bridge.

Uses csrc/batch_loader.cc (threaded row gather outside the GIL) for
datasets backed by contiguous numpy arrays — the native-path analog of the
reference's C++ data_feed/shared-memory DataLoader workers. Falls back to
numpy fancy-indexing when the native lib is unavailable.
"""
from __future__ import annotations

import ctypes
from typing import List, Sequence

import numpy as np

from ..utils import native as _native


class NativeBatchAssembler:
    """Gathers rows of one contiguous array into batches with C++ threads."""

    def __init__(self, array: np.ndarray, n_threads: int = 4,
                 queue_cap: int = 8):
        self.array = np.ascontiguousarray(array)
        self.sample_bytes = int(self.array.dtype.itemsize
                               * np.prod(self.array.shape[1:]))
        self._lib = _native.get_lib()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.bl_create(
                self.array.ctypes.data_as(ctypes.c_void_p),
                self.array.shape[0], self.sample_bytes, 0, n_threads,
                queue_cap)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def submit(self, indices: Sequence[int]) -> None:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        if self._handle is not None:
            self._lib.bl_submit(self._handle,
                                idx.ctypes.data_as(ctypes.c_void_p),
                                len(idx))
        else:
            self._fallback_queue = getattr(self, "_fallback_queue", [])
            self._fallback_queue.append(idx)

    def next(self, batch_len: int) -> np.ndarray:
        shape = (batch_len,) + self.array.shape[1:]
        if self._handle is not None:
            out = np.empty(shape, dtype=self.array.dtype)
            n = self._lib.bl_next(self._handle,
                                  out.ctypes.data_as(ctypes.c_void_p))
            assert n == out.nbytes, (n, out.nbytes)
            return out
        idx = self._fallback_queue.pop(0)
        return self.array[idx]

    def close(self):
        if self._handle is not None:
            self._lib.bl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
