"""paddle_tpu.io: datasets, samplers, DataLoader.

~ python/paddle/io/ (fluid/reader.py:273 DataLoader, fluid/dataloader/).
The multiprocess shared-memory LoDTensor transport of the reference
(dataloader_iter.py:341) is replaced by a thread-pool prefetcher: workers
produce numpy batches (GIL released inside numpy/IO), and device transfer
overlaps compute via jax async dispatch. TPU input pipelines are
host-compute bound, not IPC bound, so threads + double buffering is the
idiomatic design.
"""
from __future__ import annotations

import os
import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core import generator as _gen
from ..core.tensor import Tensor


class Dataset:
    """~ python/paddle/io/Dataset (fluid/dataloader/dataset.py:31)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: List):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t._value)[idx] if isinstance(t, Tensor)
                     else np.asarray(t)[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __getitem__(self, idx):
        d = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if d == 0 else self.cum[d - 1]
        return self.datasets[d][idx - prev]

    def __len__(self):
        return int(self.cum[-1])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ComposeDataset(Dataset):
    """~ paddle.io.ComposeDataset: zip map-style datasets field-wise."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets must not be empty")
        n = len(self.datasets[0])
        for d in self.datasets[1:]:
            if len(d) != n:
                raise ValueError("all datasets must have the same length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, (tuple, list)):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    g = generator or _gen.default_generator()
    perm = np.asarray(
        __import__("jax").random.permutation(g.next_key(), n))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    """~ fluid/dataloader/sampler.py:22."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        g = self.generator or _gen.default_generator()
        import jax
        if self.replacement:
            idx = jax.random.randint(g.next_key(), (self.num_samples,), 0, n)
        else:
            idx = jax.random.permutation(g.next_key(), n)[:self.num_samples]
        return iter(np.asarray(idx).tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng(
            _gen.default_generator().next_key()[0].item() & 0x7FFFFFFF)
        idx = rng.choice(len(self.weights), size=self.num_samples,
                         replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """~ fluid/dataloader/batch_sampler.py:21."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """~ fluid/dataloader/batch_sampler.py DistributedBatchSampler:154 —
    pads/partitions the index space across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n + self.nranks - 1) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - n]
        local = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """~ fluid/dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(col))
                            for col in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class _DataLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self._batches = iter(loader._index_iter())
        self._queue: queue.Queue = queue.Queue(
            maxsize=max(2, loader.prefetch_factor))
        self._threads = []
        self._stop = threading.Event()
        self._n_emitted = 0
        self._n_done = 0
        nw = max(1, loader.num_workers)
        self._work_q: queue.Queue = queue.Queue(maxsize=nw * 2)
        self._out = {}
        self._out_lock = threading.Lock()
        self._next_seq = 0
        self._done_workers = 0
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        for _ in range(nw):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)
        self._feeder.start()

    def _feed(self):
        seq = 0
        for b in self._batches:
            if self._stop.is_set():
                return
            self._work_q.put((seq, b))
            seq += 1
        for _ in self._threads:
            self._work_q.put(None)
        self._total = seq

    def _worker(self):
        while not self._stop.is_set():
            item = self._work_q.get()
            if item is None:
                self._queue.put(None)
                return
            seq, idx_batch = item
            try:
                data = self.loader._fetch(idx_batch)
                self._queue.put((seq, data))
            except Exception as e:  # propagate
                self._queue.put((seq, e))

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            with self._out_lock:
                if self._next_seq in self._out:
                    data = self._out.pop(self._next_seq)
                    self._next_seq += 1
                    if isinstance(data, Exception):
                        raise data
                    return self.loader._to_tensors(data)
            if self._done_workers >= len(self._threads) and not self._out:
                raise StopIteration
            item = self._queue.get()
            if item is None:
                self._done_workers += 1
                continue
            seq, data = item
            with self._out_lock:
                self._out[seq] = data

    def __del__(self):
        self._stop.set()


class DataLoader:
    """~ paddle.io.DataLoader (fluid/reader.py:273)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.persistent_workers = persistent_workers
        self._persistent_iter = None
        self.prefetch_factor = prefetch_factor
        self.return_list = return_list
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def _index_iter(self):
        return iter(self.batch_sampler)

    def _fetch(self, idx_batch):
        samples = [self.dataset[i] for i in idx_batch]
        return self.collate_fn(samples)

    def _to_tensors(self, data):
        if isinstance(data, np.ndarray):
            return Tensor(data)
        if isinstance(data, (list, tuple)):
            return type(data)(self._to_tensors(d) for d in data)
        if isinstance(data, dict):
            return {k: self._to_tensors(v) for k, v in data.items()}
        return data

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_sync()
        if self.use_shared_memory and not self._holds_device_arrays():
            # worker PROCESSES over the native shm ring (the reference's
            # multiprocess+shared-memory mode); threads otherwise
            from ..utils import native
            if native.available() and hasattr(os, "fork"):
                from .shm_channel import MultiprocessDataLoaderIter
                if not self.persistent_workers:
                    return MultiprocessDataLoaderIter(self)
                # persistent workers: fork once, reuse processes + ring
                # across epochs (fork of a JAX-loaded parent costs tens of
                # ms per worker — dominates short epochs); an iterator that
                # shut down (worker error / stall) cleared the cache and is
                # rebuilt fresh here
                if self._persistent_iter is None:
                    self._persistent_iter = MultiprocessDataLoaderIter(
                        self, persistent=True)
                else:
                    self._persistent_iter.start_epoch()
                return self._persistent_iter
        if self.persistent_workers and self.num_workers > 0:
            import warnings
            warnings.warn(
                "persistent_workers=True has no effect on the thread "
                "fallback path (native shm unavailable or dataset holds "
                "device arrays): workers are threads recreated per epoch",
                stacklevel=2)
        return _DataLoaderIter(self)

    def _holds_device_arrays(self) -> bool:
        """Forked workers must never touch XLA state (jax is multithreaded;
        fork + device access can deadlock). Recurse through wrapper
        datasets and probe one sample: anything yielding live device
        arrays stays on the thread path. Cached — the probe costs one
        __getitem__ (and possibly an RNG draw), so it must not repeat
        every epoch."""
        cached = getattr(self, "_fork_safe_cache", None)
        if cached is not None:
            return cached
        result = self._probe_device_arrays()
        self._fork_safe_cache = result
        return result

    def _probe_device_arrays(self) -> bool:
        import jax

        def ds_has_tensors(ds) -> bool:
            if isinstance(ds, TensorDataset):
                return True
            if isinstance(ds, Subset):
                return ds_has_tensors(ds.dataset)
            if isinstance(ds, (ConcatDataset, ComposeDataset)):
                return any(ds_has_tensors(d) for d in ds.datasets)
            return False

        if ds_has_tensors(self.dataset):
            return True
        try:  # probe one sample's tree for device arrays
            sample = self.dataset[0]
        except Exception:  # noqa: BLE001 — leave it to the worker to fail
            return False
        leaves = jax.tree.leaves(
            sample, is_leaf=lambda x: isinstance(x, (Tensor, jax.Array)))
        return any(isinstance(v, (Tensor, jax.Array))
                   or isinstance(getattr(v, "_value", None), jax.Array)
                   for v in leaves)

    def _iter_sync(self):
        for idx_batch in self._index_iter():
            yield self._to_tensors(self._fetch(idx_batch))

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self._to_tensors(self.collate_fn(batch))
                batch = []
        if batch and not self.drop_last:
            yield self._to_tensors(self.collate_fn(batch))

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)


def get_worker_info():
    return None
