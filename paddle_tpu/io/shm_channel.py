"""Shared-memory record channel + multiprocess DataLoader workers.

~ the reference's multiprocess DataLoader transport
(fluid/dataloader/dataloader_iter.py:341 _DataLoaderIterMultiProcess,
`_worker_loop` :402, shared-memory LoDTensor handoff :542-546 over
memory/allocation/mmap_allocator.h): worker PROCESSES (not threads — real
CPU parallelism for python-heavy datasets) fetch and serialize batches
into the native shm ring (csrc/shm_ring.cc); the parent deserializes in
ticket order. Falls back to multiprocessing queues when the native lib is
unavailable.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import time
from typing import Optional

from ..utils import native


class ShmRing:
    """ctypes view over one csrc/shm_ring.cc segment."""

    def __init__(self, name: str, slot_size: int = 1 << 20,
                 n_slots: int = 8, create: bool = False):
        lib = native.get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.name = name.encode()
        if create:
            self._h = lib.shm_ring_create(self.name, slot_size, n_slots)
        else:
            self._h = lib.shm_ring_open(self.name)
        if not self._h:
            raise OSError(f"shm_ring {'create' if create else 'open'} "
                          f"failed for {name}")
        self.slot_size = lib.shm_ring_slot_size(self._h)
        self._buf = ctypes.create_string_buffer(self.slot_size)

    def write(self, payload: bytes) -> int:
        r = self._lib.shm_ring_write(self._h, payload, len(payload))
        if r < 0:
            raise ValueError(
                f"record of {len(payload)} bytes exceeds slot_size "
                f"{self.slot_size}; construct the ring with a larger "
                "slot_size")
        return r

    def read(self, timeout_us: int = -1) -> Optional[bytes]:
        """Next record in ticket order; None on timeout. b'' is a valid
        (empty) record, distinct from timeout (C side returns -2)."""
        n = self._lib.shm_ring_read(self._h, self._buf, self.slot_size,
                                    timeout_us)
        if n == -2:
            return None
        if n == -1:
            raise ValueError("shm_ring record larger than reader buffer")
        # copy exactly n bytes (ctypes .raw would copy the whole slot)
        return ctypes.string_at(self._buf, n)

    def close(self):
        if self._h:
            self._lib.shm_ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


_STOP_WORKER = b"__stop__"


def _mp_worker_loop(loader, work_q, ring_name, err_q, worker_id,
                    worker_init_fn):
    """Worker process body (~ dataloader_iter.py _worker_loop:402). Every
    failure mode reports to err_q — the parent must never have to guess
    from a timeout."""
    try:
        ring = ShmRing(ring_name, create=False)
    except (OSError, RuntimeError) as e:
        err_q.put((worker_id, repr(e)))
        return
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        while True:
            item = work_q.get()
            if item is None:
                ring.write(pickle.dumps(("done", worker_id, None)))
                return
            seq, idx_batch = item
            try:
                data = loader._fetch(idx_batch)
                blob = pickle.dumps(("ok", seq, data), protocol=4)
            except Exception as e:  # noqa: BLE001 — shipped to parent
                blob = pickle.dumps(("err", seq, repr(e)), protocol=4)
            ring.write(blob)
    except Exception as e:  # noqa: BLE001 — init fn / oversize record
        err_q.put((worker_id, repr(e)))
    finally:
        ring.close()


class MultiprocessDataLoaderIter:
    """Parent-side iterator over N worker processes + one shm ring.

    persistent=True keeps the worker processes (and the ring) alive across
    epochs: forking a JAX-loaded parent costs tens of ms per worker, which
    dominates short epochs (measured: fork ~0.16s for 4 workers vs ~2.5ms
    of actual per-epoch transport). Epochs are then purely parent-side
    bookkeeping — the feeder streams each epoch's index batches with a
    continuing absolute sequence number and workers never notice epoch
    boundaries (~ reference DataLoader persistent_workers)."""

    def __init__(self, loader, slot_size: int = 4 << 20,
                 persistent: bool = False):
        import multiprocessing as mp
        self.loader = loader
        self.persistent = persistent
        nw = max(1, loader.num_workers)
        self._ring_name = f"/pt_dl_{os.getpid()}_{id(self)}"
        self._ring = ShmRing(self._ring_name, slot_size=slot_size,
                             n_slots=max(4, 2 * nw), create=True)
        import threading
        ctx = mp.get_context("fork")  # workers touch only dataset + numpy
        # bounded: a feeder thread streams index batches with backpressure
        # (the thread path's _feed pattern) instead of materializing the
        # whole epoch's indices in the queue
        self._work_q = ctx.Queue(maxsize=nw * 2)
        self._err_q = ctx.Queue()
        self._procs = []
        for w in range(nw):
            p = ctx.Process(target=_mp_worker_loop,
                            args=(loader, self._work_q, self._ring_name,
                                  self._err_q, w, loader.worker_init_fn),
                            daemon=True)
            p.start()
            self._procs.append(p)
        self._total = len(loader.batch_sampler)
        self._stopping = threading.Event()
        self._feed_error = None
        self._epoch_base = 0
        self._feed_stop = threading.Event()
        self._feeder = threading.Thread(
            target=self._feed, args=(0, self._feed_stop), daemon=True)
        self._feeder.start()
        self._done_workers = 0
        self._next_seq = 0
        self._stash = {}

    def start_epoch(self):
        """Re-arm a persistent iterator for the next epoch (discarding any
        leftovers of an aborted one)."""
        import threading
        self._epoch_base += self._total
        self._next_seq = self._epoch_base
        self._stash = {k: v for k, v in self._stash.items()
                       if k >= self._epoch_base}
        if self._feeder.is_alive():
            self._feed_stop.set()
            self._feeder.join(timeout=10)
        self._feed_error = None
        self._feed_stop = threading.Event()
        self._feeder = threading.Thread(
            target=self._feed, args=(self._epoch_base, self._feed_stop),
            daemon=True)
        self._feeder.start()

    def _feed(self, seq_base, stop):
        import queue as _q

        def bounded_put(item) -> bool:
            while not (self._stopping.is_set() or stop.is_set()):
                try:  # teardown races surface as OSError/ValueError
                    self._work_q.put(item, timeout=0.2)
                    return True
                except _q.Full:
                    continue
                except (OSError, ValueError):
                    return False
            return False

        try:
            for i, idx_batch in enumerate(self.loader._index_iter()):
                if not bounded_put((seq_base + i, list(idx_batch))):
                    return
        except Exception as e:  # noqa: BLE001 — user sampler failure
            self._feed_error = e  # surfaced by __next__, never swallowed
        if not self.persistent:
            for _ in self._procs:
                bounded_put(None)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._next_seq in self._stash:
                data = self._stash.pop(self._next_seq)
                self._next_seq += 1
                return self.loader._to_tensors(data)
            if self._next_seq >= self._epoch_base + self._total:
                if not self.persistent:
                    self._shutdown(graceful=True)
                raise StopIteration
            blob = None
            for _ in range(30):  # 1s slices: react to errors fast
                blob = self._ring.read(timeout_us=1_000_000)
                if blob is not None:
                    break
                if self._feed_error is not None:
                    err = self._feed_error
                    self._shutdown()
                    raise RuntimeError(
                        "DataLoader batch sampler failed") from err
                self._check_errors()  # raises the reported cause
                if any(not p.is_alive() and p.exitcode not in (0, None)
                       for p in self._procs):
                    self._shutdown()
                    raise RuntimeError(
                        "DataLoader worker died without reporting "
                        f"(exitcodes {[p.exitcode for p in self._procs]})")
            if blob is None:
                self._shutdown()
                raise TimeoutError("DataLoader workers stalled (30s)")
            kind, seq, data = pickle.loads(blob)
            if kind == "done":
                self._done_workers += 1
                continue
            if seq < self._epoch_base:
                # stale record ('ok' OR 'err') from an aborted previous
                # epoch — an old error must not kill the healthy new epoch
                continue
            if kind == "err":
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed: {data}")
            self._stash[seq] = data

    def _check_errors(self):
        try:
            wid, err = self._err_q.get_nowait()
        except Exception:  # noqa: BLE001 — queue empty
            return
        self._shutdown()
        raise RuntimeError(f"DataLoader worker {wid} failed to start: {err}")

    def _shutdown(self, graceful: bool = False):
        if getattr(self, "_shut", False):
            return  # idempotent: a second call must not touch the closed ring
        self._shut = True
        # a shut-down persistent iterator must never be reused by the
        # loader's __iter__ cache
        if getattr(self.loader, "_persistent_iter", None) is self:
            self.loader._persistent_iter = None
        if self.persistent:
            # persistent workers never saw epoch sentinels; queue the stop
            # tokens now so they can exit cleanly before the terminate path
            # (per-put guard: one full slot must not abandon the rest —
            # other workers drain stale items and free slots)
            for _ in self._procs:
                try:
                    self._work_q.put_nowait(None)
                except Exception:  # noqa: BLE001 — full/closed queue
                    pass
            graceful = True
        if graceful:
            # End of a fully-consumed epoch: sentinels are already queued, so
            # let workers drain them and exit on their own. Terminating
            # immediately races a worker still mid-fork under machine load —
            # it would be killed before even running worker_init_fn. Drain
            # the ring for "done" markers first so the joins below are
            # near-instant in the normal case; a genuinely wedged worker
            # costs at most the 5s budget before falling through to
            # terminate.
            deadline = time.time() + 5.0
            while (self._done_workers < len(self._procs)
                   and time.time() < deadline):
                blob = self._ring.read(timeout_us=200_000)
                if blob is None:
                    continue
                kind = pickle.loads(blob)[0]
                if kind == "done":
                    self._done_workers += 1
            for p in self._procs:
                p.join(timeout=max(0.0, deadline - time.time()) + 0.5)
        self._stopping.set()  # unblock the feeder's bounded puts
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        if hasattr(self, "_feeder"):
            self._feeder.join(timeout=5)
        self._ring.close()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:  # noqa: BLE001
            pass
