"""Probability distributions.

~ python/paddle/distribution/ (Normal/Uniform/Categorical/Beta/Dirichlet/
ExponentialFamily + kl_divergence registry). Sampling consumes the global
Generator; densities are jnp formulas.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import generator as _gen
from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


def _t(x):
    """Keep caller Tensors (so grads flow to them); wrap raw values."""
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x, jnp.float32))


class Distribution:
    """~ distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op("dist_prob", lambda lv: jnp.exp(lv),
                        self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        z = jax.random.normal(_gen.next_key(), shape, jnp.float32)
        return Tensor(z * self.scale._value + self.loc._value)

    def log_prob(self, value):
        def fn(v, loc, scale):
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return apply_op("normal_log_prob", fn, value, self.loc, self.scale)

    def entropy(self):
        def fn(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return apply_op("normal_entropy", fn, self.scale)

    def cdf(self, value):
        def fn(v, loc, scale):
            return 0.5 * (1 + jax.scipy.special.erf(
                (v - loc) / (scale * math.sqrt(2))))
        return apply_op("normal_cdf", fn, value, self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(np.broadcast_shapes(
            self.low._value.shape, self.high._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_gen.next_key(), shape)
        return Tensor(self.low._value + u * (self.high._value
                                             - self.low._value))

    def log_prob(self, value):
        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op("uniform_log_prob", fn, value, self.low, self.high)

    def entropy(self):
        return apply_op("uniform_entropy",
                        lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = Tensor(jnp.log(jnp.maximum(_v(probs), 1e-30)))
        super().__init__(self.logits._value.shape[:-1])

    @property
    def probs(self):
        return apply_op("cat_probs", lambda l: jax.nn.softmax(l, -1),
                        self.logits)

    def sample(self, shape=()):
        out = jax.random.categorical(_gen.next_key(), self.logits._value,
                                     shape=tuple(shape) + self._batch_shape)
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        def fn(logits, v):
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), -1)[..., 0]
        return apply_op("cat_log_prob", fn, self.logits, value)

    def entropy(self):
        def fn(logits):
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)
        return apply_op("cat_entropy", fn, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = _t(probs)
        super().__init__(self.probs_t._value.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            _gen.next_key(), self.probs_t._value, shape).astype(jnp.float32))

    def log_prob(self, value):
        def fn(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op("bern_log_prob", fn, self.probs_t, value)

    def entropy(self):
        def fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op("bern_entropy", fn, self.probs_t)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(np.broadcast_shapes(
            self.alpha._value.shape, self.beta._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        out = jax.random.beta(_gen.next_key(), self.alpha._value,
                              self.beta._value, shape)
        return Tensor(out)

    def log_prob(self, value):
        def fn(v, a, b):
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return apply_op("beta_log_prob", fn, value, self.alpha, self.beta)

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply_op("beta_entropy", fn, self.alpha, self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        c = self.concentration._value
        super().__init__(c.shape[:-1], c.shape[-1:])

    def sample(self, shape=()):
        out = jax.random.dirichlet(_gen.next_key(),
                                   self.concentration._value,
                                   tuple(shape) + self._batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        def fn(v, c):
            lnorm = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                     - jax.scipy.special.gammaln(jnp.sum(c, -1)))
            return jnp.sum((c - 1) * jnp.log(v), -1) - lnorm
        return apply_op("dirichlet_log_prob", fn, value, self.concentration)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._value.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(_gen.next_key(), shape)
                      / self.rate._value)

    def log_prob(self, value):
        return apply_op("exp_log_prob",
                        lambda v, r: jnp.log(r) - r * v, value, self.rate)

    def entropy(self):
        return apply_op("exp_entropy", lambda r: 1.0 - jnp.log(r), self.rate)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        g = jax.random.gumbel(_gen.next_key(), shape)
        return Tensor(self.loc._value + self.scale._value * g)

    def log_prob(self, value):
        def fn(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return apply_op("gumbel_log_prob", fn, value, self.loc, self.scale)


# ---- KL registry -----------------------------------------------------------

def kl_divergence(p: Distribution, q: Distribution):
    """~ distribution/kl.py kl_divergence with a (type,type) registry."""
    key = (type(p).__name__, type(q).__name__)
    if key == ("Normal", "Normal"):
        def fn(lp, sp, lq, sq):
            var_ratio = (sp / sq) ** 2
            t1 = ((lp - lq) / sq) ** 2
            return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
        return apply_op("kl_normal", fn, p.loc, p.scale, q.loc, q.scale)
    if key == ("Categorical", "Categorical"):
        def fn(lp, lq):
            a = jax.nn.log_softmax(lp, -1)
            b = jax.nn.log_softmax(lq, -1)
            return jnp.sum(jnp.exp(a) * (a - b), -1)
        return apply_op("kl_cat", fn, p.logits, q.logits)
    if key == ("Uniform", "Uniform"):
        def fn(alo, ahi, blo, bhi):
            return jnp.log((bhi - blo) / (ahi - alo))
        return apply_op("kl_uniform", fn, p.low, p.high, q.low, q.high)
    if key == ("Beta", "Beta"):
        def fn(a1, b1, a2, b2):
            dg = jax.scipy.special.digamma
            gl = jax.scipy.special.gammaln
            lb1 = gl(a1) + gl(b1) - gl(a1 + b1)
            lb2 = gl(a2) + gl(b2) - gl(a2 + b2)
            return (lb2 - lb1 + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                    + (a2 - a1 + b2 - b1) * dg(a1 + b1))
        return apply_op("kl_beta", fn, p.alpha, p.beta, q.alpha, q.beta)
    raise NotImplementedError(f"kl_divergence not registered for {key}")
