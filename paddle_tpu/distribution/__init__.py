"""Probability distributions.

~ python/paddle/distribution/ (Normal/Uniform/Categorical/Beta/Dirichlet/
ExponentialFamily + kl_divergence registry). Sampling consumes the global
Generator; densities are jnp formulas.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import generator as _gen
from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


def _t(x):
    """Keep caller Tensors (so grads flow to them); wrap raw values."""
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x, jnp.float32))


class Distribution:
    """~ distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op("dist_prob", lambda lv: jnp.exp(lv),
                        self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        z = jax.random.normal(_gen.next_key(), shape, jnp.float32)
        return Tensor(z * self.scale._value + self.loc._value)

    def log_prob(self, value):
        def fn(v, loc, scale):
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return apply_op("normal_log_prob", fn, value, self.loc, self.scale)

    def entropy(self):
        def fn(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return apply_op("normal_entropy", fn, self.scale)

    def cdf(self, value):
        def fn(v, loc, scale):
            return 0.5 * (1 + jax.scipy.special.erf(
                (v - loc) / (scale * math.sqrt(2))))
        return apply_op("normal_cdf", fn, value, self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(np.broadcast_shapes(
            self.low._value.shape, self.high._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_gen.next_key(), shape)
        return Tensor(self.low._value + u * (self.high._value
                                             - self.low._value))

    def log_prob(self, value):
        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op("uniform_log_prob", fn, value, self.low, self.high)

    def entropy(self):
        return apply_op("uniform_entropy",
                        lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = Tensor(jnp.log(jnp.maximum(_v(probs), 1e-30)))
        super().__init__(self.logits._value.shape[:-1])

    @property
    def probs(self):
        return apply_op("cat_probs", lambda l: jax.nn.softmax(l, -1),
                        self.logits)

    def sample(self, shape=()):
        out = jax.random.categorical(_gen.next_key(), self.logits._value,
                                     shape=tuple(shape) + self._batch_shape)
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        def fn(logits, v):
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), -1)[..., 0]
        return apply_op("cat_log_prob", fn, self.logits, value)

    def entropy(self):
        def fn(logits):
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)
        return apply_op("cat_entropy", fn, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = _t(probs)
        super().__init__(self.probs_t._value.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            _gen.next_key(), self.probs_t._value, shape).astype(jnp.float32))

    def log_prob(self, value):
        def fn(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op("bern_log_prob", fn, self.probs_t, value)

    def entropy(self):
        def fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op("bern_entropy", fn, self.probs_t)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(np.broadcast_shapes(
            self.alpha._value.shape, self.beta._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        out = jax.random.beta(_gen.next_key(), self.alpha._value,
                              self.beta._value, shape)
        return Tensor(out)

    def log_prob(self, value):
        def fn(v, a, b):
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return apply_op("beta_log_prob", fn, value, self.alpha, self.beta)

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply_op("beta_entropy", fn, self.alpha, self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        c = self.concentration._value
        super().__init__(c.shape[:-1], c.shape[-1:])

    def sample(self, shape=()):
        out = jax.random.dirichlet(_gen.next_key(),
                                   self.concentration._value,
                                   tuple(shape) + self._batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        def fn(v, c):
            lnorm = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                     - jax.scipy.special.gammaln(jnp.sum(c, -1)))
            return jnp.sum((c - 1) * jnp.log(v), -1) - lnorm
        return apply_op("dirichlet_log_prob", fn, value, self.concentration)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._value.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(_gen.next_key(), shape)
                      / self.rate._value)

    def log_prob(self, value):
        return apply_op("exp_log_prob",
                        lambda v, r: jnp.log(r) - r * v, value, self.rate)

    def entropy(self):
        return apply_op("exp_entropy", lambda r: 1.0 - jnp.log(r), self.rate)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        g = jax.random.gumbel(_gen.next_key(), shape)
        return Tensor(self.loc._value + self.scale._value * g)

    def log_prob(self, value):
        def fn(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return apply_op("gumbel_log_prob", fn, value, self.loc, self.scale)


# ---- KL registry -----------------------------------------------------------

def kl_divergence(p: Distribution, q: Distribution):
    """~ distribution/kl.py kl_divergence with a (type,type) registry."""
    fn = _lookup_kl(p, q)
    if fn is not None:
        return fn(p, q)
    key = (type(p).__name__, type(q).__name__)
    if key == ("Normal", "Normal"):
        def fn(lp, sp, lq, sq):
            var_ratio = (sp / sq) ** 2
            t1 = ((lp - lq) / sq) ** 2
            return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
        return apply_op("kl_normal", fn, p.loc, p.scale, q.loc, q.scale)
    if key == ("Categorical", "Categorical"):
        def fn(lp, lq):
            a = jax.nn.log_softmax(lp, -1)
            b = jax.nn.log_softmax(lq, -1)
            return jnp.sum(jnp.exp(a) * (a - b), -1)
        return apply_op("kl_cat", fn, p.logits, q.logits)
    if key == ("Uniform", "Uniform"):
        def fn(alo, ahi, blo, bhi):
            return jnp.log((bhi - blo) / (ahi - alo))
        return apply_op("kl_uniform", fn, p.low, p.high, q.low, q.high)
    if key == ("Beta", "Beta"):
        def fn(a1, b1, a2, b2):
            dg = jax.scipy.special.digamma
            gl = jax.scipy.special.gammaln
            lb1 = gl(a1) + gl(b1) - gl(a1 + b1)
            lb2 = gl(a2) + gl(b2) - gl(a2 + b2)
            return (lb2 - lb1 + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                    + (a2 - a1 + b2 - b1) * dg(a1 + b1))
        return apply_op("kl_beta", fn, p.alpha, p.beta, q.alpha, q.beta)
    raise NotImplementedError(f"kl_divergence not registered for {key}")


# ---- registry + composite distributions ------------------------------------

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """~ paddle.distribution.register_kl (distribution/kl.py): decorator
    registering a KL implementation for a (type, type) pair; dispatch walks
    the MRO of both args so subclasses inherit registrations."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def _lookup_kl(p, q):
    best = None
    best_rank = None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            rank = (type(p).__mro__.index(cp), type(q).__mro__.index(cq))
            if best_rank is None or rank < best_rank:
                best, best_rank = fn, rank
    return best


class ExponentialFamily(Distribution):
    """~ paddle.distribution.ExponentialFamily: distributions with natural
    parameters; entropy via the Bregman identity (log-normalizer gradients),
    which jax.grad supplies directly."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0

    def entropy(self):
        nat = [n._value if isinstance(n, Tensor) else jnp.asarray(n)
               for n in self._natural_parameters]

        def fn(*nat_in):
            logz, grads = jax.value_and_grad(
                lambda ps: jnp.sum(self._log_normalizer(*ps)),
                )(tuple(nat_in))
            ent = logz - self._mean_carrier_measure
            for np_, g in zip(nat_in, grads):
                ent = ent - jnp.sum(np_ * g)
            return ent
        return apply_op("ef_entropy", fn, *[Tensor(n) for n in nat])


class Independent(Distribution):
    """~ paddle.distribution.Independent: reinterprets trailing batch dims of
    ``base`` as event dims (sums log_prob over them)."""

    def __init__(self, base, reinterpreted_batch_rank=0):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        bs = tuple(base.batch_shape)
        k = len(bs) - self.reinterpreted_batch_rank
        super().__init__(bs[:k], bs[k:] + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        if self.reinterpreted_batch_rank == 0:
            return lp
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return apply_op("independent_logprob",
                        lambda v: jnp.sum(v, axis=axes), lp)

    def entropy(self):
        ent = self.base.entropy()
        if self.reinterpreted_batch_rank == 0:
            return ent
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return apply_op("independent_entropy",
                        lambda v: jnp.sum(v, axis=axes), ent)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class Multinomial(Distribution):
    """~ paddle.distribution.Multinomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = probs if isinstance(probs, Tensor) else Tensor(
            jnp.asarray(probs, jnp.float32))
        p = self.probs._value
        super().__init__(p.shape[:-1], p.shape[-1:])

    @property
    def mean(self):
        return apply_op("multinomial_mean",
                        lambda p: self.total_count * p
                        / jnp.sum(p, -1, keepdims=True), self.probs)

    @property
    def variance(self):
        def fn(p):
            pn = p / jnp.sum(p, -1, keepdims=True)
            return self.total_count * pn * (1 - pn)
        return apply_op("multinomial_var", fn, self.probs)

    def sample(self, shape=()):
        from ..core.generator import default_generator
        shape = tuple(shape)
        p = self.probs._value
        pn = p / jnp.sum(p, -1, keepdims=True)
        key = default_generator().next_key()
        # counts via total_count categorical draws, one-hot summed
        draws = jax.random.categorical(
            key, jnp.log(jnp.maximum(pn, 1e-30)),
            shape=shape + (self.total_count,) + p.shape[:-1])
        counts = jax.nn.one_hot(draws, p.shape[-1]).sum(len(shape))
        return Tensor(counts.astype(jnp.float32))

    def log_prob(self, value):
        def fn(v, p):
            pn = p / jnp.sum(p, -1, keepdims=True)
            gl = jax.scipy.special.gammaln
            return (gl(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(gl(v + 1.0), -1)
                    + jnp.sum(v * jnp.log(jnp.maximum(pn, 1e-30)), -1))
        return apply_op("multinomial_logprob", fn, value, self.probs)

    def entropy(self):
        # no closed form; Monte-Carlo estimate (matches reference's absence
        # of an exact formula — it doesn't implement entropy either)
        samples = self.sample((128,))
        lp = self.log_prob(samples)
        return apply_op("multinomial_entropy",
                        lambda v: -jnp.mean(v, axis=0), lp)


class Transform:
    """~ paddle.distribution.Transform (distribution/transform.py)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return apply_op("neg_ldj", lambda v: -v,
                        self.forward_log_det_jacobian(self.inverse(y)))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """~ paddle.distribution.AffineTransform: y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(
            jnp.asarray(loc, jnp.float32))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(
            jnp.asarray(scale, jnp.float32))

    def forward(self, x):
        return apply_op("affine_fwd", lambda v, l, s: l + s * v,
                        x, self.loc, self.scale)

    def inverse(self, y):
        return apply_op("affine_inv", lambda v, l, s: (v - l) / s,
                        y, self.loc, self.scale)

    def forward_log_det_jacobian(self, x):
        return apply_op("affine_ldj",
                        lambda v, s: jnp.broadcast_to(
                            jnp.log(jnp.abs(s)), v.shape),
                        x, self.scale)


class ExpTransform(Transform):
    """~ paddle.distribution.ExpTransform: y = exp(x)."""

    def forward(self, x):
        return apply_op("exp_fwd", jnp.exp, x)

    def inverse(self, y):
        return apply_op("exp_inv", jnp.log, y)

    def forward_log_det_jacobian(self, x):
        return apply_op("exp_ldj", lambda v: v, x)


class TransformedDistribution(Distribution):
    """~ paddle.distribution.TransformedDistribution(base, transforms)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms) if isinstance(
            transforms, (list, tuple)) else [transforms]
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = value
        ldj_terms = []
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj_terms.append(t.forward_log_det_jacobian(x))
            y = x
        lp = self.base.log_prob(y)

        def fn(base_lp, *ldjs):
            out = base_lp
            for l in ldjs:
                out = out - l
            return out
        return apply_op("transformed_logprob", fn, lp, *ldj_terms)
