"""Calibration observers for post-training quantization.

~ fluid/contrib/slim/quantization/post_training_quantization.py: the
reference offers abs_max / avg / hist / KL / mse activation-scale
algorithms (its `algo` arg). Same capability here, numpy-side (calibration
is host work; only the resulting scales enter the compiled graph).
"""
from __future__ import annotations

import numpy as np


class AbsMaxObserver:
    """Running abs-max (~ algo='abs_max')."""

    def __init__(self):
        self._max = 0.0

    def update(self, arr: np.ndarray):
        self._max = max(self._max, float(np.max(np.abs(arr))))

    def scale(self) -> float:
        return max(self._max, 1e-8)


class AvgObserver:
    """Average of per-batch abs-max (~ algo='avg')."""

    def __init__(self):
        self._sum = 0.0
        self._n = 0

    def update(self, arr: np.ndarray):
        self._sum += float(np.max(np.abs(arr)))
        self._n += 1

    def scale(self) -> float:
        return max(self._sum / max(self._n, 1), 1e-8)


class HistObserver:
    """Histogram collector with percentile or KL threshold selection
    (~ algo='hist' / algo='KL', reference PostTrainingQuantization
    _sample_histogram + _get_kl_scaling_factor)."""

    def __init__(self, bins=2048, percentile=0.99999, algo="hist"):
        self.bins = bins
        self.percentile = percentile
        self.algo = algo
        self._hist = None
        self._edges = None
        self._count = 0

    def update(self, arr: np.ndarray):
        a = np.abs(np.asarray(arr, np.float32)).ravel()
        self._count += int(a.size)
        amax = float(a.max()) if a.size else 0.0
        if self._hist is None:
            hi = max(amax, 1e-8)
            self._hist, self._edges = np.histogram(a, bins=self.bins,
                                                   range=(0.0, hi))
            return
        hi = self._edges[-1]
        if amax > hi:
            # stretch: rebin old histogram into the wider range
            new_edges = np.linspace(0.0, amax, self.bins + 1)
            centers = (self._edges[:-1] + self._edges[1:]) / 2
            idx = np.clip(np.searchsorted(new_edges, centers) - 1, 0,
                          self.bins - 1)
            new_hist = np.zeros(self.bins, self._hist.dtype)
            np.add.at(new_hist, idx, self._hist)
            self._hist, self._edges = new_hist, new_edges
        h, _ = np.histogram(a, bins=self.bins,
                            range=(0.0, self._edges[-1]))
        self._hist += h

    def _percentile_scale(self) -> float:
        total = self._hist.sum()
        if total == 0:
            return 1e-8
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self.percentile))
        return float(self._edges[min(idx + 1, self.bins)])

    def _kl_scale(self, quant_bins=128) -> float:
        """KL-divergence threshold search (TensorRT-style, mirroring the
        reference's cal_kl_threshold)."""
        hist = self._hist.astype(np.float64)
        edges = self._edges
        total = hist.sum()
        if total == 0:
            return 1e-8
        # Coarsen to the data's support first: the KL search assumes a
        # DENSE histogram (TensorRT calibrates 2048 bins over millions
        # of samples). Over a few hundred samples most bins hold 0-or-1
        # counts and the divergence fits bin noise — measured on a
        # post-ReLU activation set (1024 samples): threshold 0.97 vs
        # absmax 2.67, 17% mean activation error; after halving to 256
        # bins the search picks 2.57 and the error drops to 0.8%.
        bins = len(hist)
        while bins > quant_bins and bins // 2 >= quant_bins \
                and bins > max(quant_bins, self._count // 4) \
                and bins % 2 == 0:
            hist = hist.reshape(bins // 2, 2).sum(axis=1)
            edges = edges[::2]
            bins //= 2
        step = max(1, bins // 256)
        best_div, best_i = np.inf, bins
        for i in range(quant_bins, bins + 1, step):
            p = hist[:i].copy()
            p[i - 1] += hist[i:].sum()  # clip outliers into last bin
            p /= p.sum()
            # quantize the i bins down to quant_bins then expand back
            factor = i / quant_bins
            q = np.zeros(i)
            for j in range(quant_bins):
                lo, hi = int(j * factor), max(int((j + 1) * factor),
                                              int(j * factor) + 1)
                chunk = hist[lo:hi]
                nz = chunk > 0
                if nz.any():
                    q[lo:hi][nz] = chunk[nz].sum() / nz.sum()
            qs = q.sum()
            if qs == 0:
                continue
            q /= qs
            mask = p > 0
            div = float(np.sum(p[mask] * np.log(
                p[mask] / np.maximum(q[mask], 1e-12))))
            if div < best_div:
                best_div, best_i = div, i
        return float(edges[best_i])

    def scale(self) -> float:
        if self._hist is None:
            return 1e-8
        if self.algo == "KL":
            return max(self._kl_scale(), 1e-8)
        return max(self._percentile_scale(), 1e-8)


def make_observer(algo: str):
    if algo == "abs_max":
        return AbsMaxObserver()
    if algo == "avg":
        return AvgObserver()
    if algo in ("hist", "KL"):
        return HistObserver(algo=algo)
    raise ValueError(f"unknown calibration algo {algo!r} "
                     "(want abs_max|avg|hist|KL)")
