"""Quantization toolkit (slim).

~ python/paddle/fluid/contrib/slim/quantization/ (quantization_pass.py QAT
fake-quant insertion, imperative/qat.py ImperativeQuantAware,
post_training_quantization.py). TPU-native: fake-quant is a straight-
through-estimator op pair (quant sim in the graph); int8 execution on TPU
rides XLA's native int8 matmul when exported.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..ops.dispatch import apply_op


def fake_quantize_dequantize(x, scale, bits=8):
    """Symmetric per-tensor fake quant with straight-through gradient
    (~ fake_quantize_dequantize_moving_average_abs_max op)."""
    import jax
    qmax = 2 ** (bits - 1) - 1

    def fn(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
        deq = q * s / qmax
        # straight-through: gradient of round treated as identity
        return v + jax.lax.stop_gradient(deq - v)
    return apply_op("fake_quant_dequant", fn, x, scale)


class FakeQuant(nn.Layer):
    """Moving-average abs-max observer + fake quant (~ imperative/qat.py)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale", Tensor(jnp.asarray(1.0, jnp.float32)))
        self._observed = False

    def forward(self, x):
        if self.training:
            cur = float(jnp.max(jnp.abs(x._value)))
            if not self._observed:
                self.scale._value = jnp.asarray(cur, jnp.float32)
                self._observed = True
            else:
                self.scale._value = (self.momentum * self.scale._value
                                     + (1 - self.momentum) * cur)
        return fake_quantize_dequantize(x, self.scale, self.bits)


class QuantedLinear(nn.Layer):
    def __init__(self, linear: nn.Linear, bits=8):
        super().__init__()
        self.inner = linear
        self.act_quant = FakeQuant(bits)
        self.w_quant = FakeQuant(bits)

    def forward(self, x):
        x = self.act_quant(x)
        w = self.w_quant(self.inner.weight)
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, conv: nn.Conv2D, bits=8):
        super().__init__()
        self.inner = conv
        self.act_quant = FakeQuant(bits)
        self.w_quant = FakeQuant(bits)

    def forward(self, x):
        x = self.act_quant(x)
        w = self.w_quant(self.inner.weight)
        from ..nn import functional as F
        c = self.inner
        return F.conv2d(x, w, c.bias, c.stride, c.padding, c.dilation,
                        c.groups, c.data_format)


class ImperativeQuantAware:
    """QAT transformer (~ slim/quantization/imperative/qat.py:104):
    swaps Linear/Conv2D sublayers for fake-quantized versions."""

    def __init__(self, bits=8, quantizable_layer_type=("Linear", "Conv2D")):
        self.bits = bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model: nn.Layer) -> nn.Layer:
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            if cls == "Linear" and "Linear" in self.types:
                model._sub_layers[name] = QuantedLinear(sub, self.bits)
            elif cls == "Conv2D" and "Conv2D" in self.types:
                model._sub_layers[name] = QuantedConv2D(sub, self.bits)
            else:
                self.quantize(sub)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ calibration (~ post_training_quantization.py): run calibration
    batches, record abs-max scales per quantized layer, emit int8 weights +
    scales."""

    def __init__(self, model: nn.Layer, data_loader, bits=8,
                 algo="abs_max"):
        self.model = model
        self.loader = data_loader
        self.bits = bits

    def quantize(self):
        qat = ImperativeQuantAware(self.bits)
        model = qat.quantize(self.model)
        model.train()
        from ..autograd import no_grad
        with no_grad():
            for batch in self.loader:
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                model(x)
        model.eval()
        return model

    def save_quantized_model(self, save_model_path, **kw):
        from ..framework.io import save
        state = {}
        qmax = 2 ** (self.bits - 1) - 1
        for name, layer in self.model.named_sublayers():
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                w = layer.inner.weight._value
                s = float(layer.w_quant.scale._value)
                q = np.clip(np.round(np.asarray(w) / max(s, 1e-8) * qmax),
                            -qmax, qmax).astype(np.int8)
                state[f"{name}.weight_int8"] = q
                state[f"{name}.weight_scale"] = s
        save(state, save_model_path + ".pdquant")
        return state
