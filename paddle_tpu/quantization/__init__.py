"""Quantization toolkit (slim).

~ python/paddle/fluid/contrib/slim/quantization/ (quantization_pass.py QAT
fake-quant insertion, imperative/qat.py ImperativeQuantAware,
post_training_quantization.py). TPU-native: fake-quant is a straight-
through-estimator op pair (quant sim in the graph); int8 execution on TPU
rides XLA's native int8 matmul when exported.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..ops.dispatch import apply_op
from .int8 import (Int8Linear, convert_to_int8,  # noqa: F401
                   quantize_weight_per_channel)
from .observers import (AbsMaxObserver, AvgObserver,  # noqa: F401
                        HistObserver, make_observer)


def fake_quantize_dequantize(x, scale, bits=8):
    """Symmetric per-tensor fake quant with straight-through gradient
    (~ fake_quantize_dequantize_moving_average_abs_max op)."""
    import jax
    qmax = 2 ** (bits - 1) - 1

    def fn(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
        deq = q * s / qmax
        # straight-through: gradient of round treated as identity
        return v + jax.lax.stop_gradient(deq - v)
    return apply_op("fake_quant_dequant", fn, x, scale)


class FakeQuant(nn.Layer):
    """Moving-average abs-max observer + fake quant (~ imperative/qat.py)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale", Tensor(jnp.asarray(1.0, jnp.float32)))
        self._observed = False

    def forward(self, x):
        if self.training:
            cur = float(jnp.max(jnp.abs(x._value)))
            if not self._observed:
                self.scale._value = jnp.asarray(cur, jnp.float32)
                self._observed = True
            else:
                self.scale._value = (self.momentum * self.scale._value
                                     + (1 - self.momentum) * cur)
        return fake_quantize_dequantize(x, self.scale, self.bits)


class QuantedLinear(nn.Layer):
    def __init__(self, linear: nn.Linear, bits=8):
        super().__init__()
        self.inner = linear
        self.act_quant = FakeQuant(bits)
        self.w_quant = FakeQuant(bits)

    def forward(self, x):
        x = self.act_quant(x)
        w = self.w_quant(self.inner.weight)
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, conv: nn.Conv2D, bits=8):
        super().__init__()
        self.inner = conv
        self.act_quant = FakeQuant(bits)
        self.w_quant = FakeQuant(bits)

    def forward(self, x):
        x = self.act_quant(x)
        w = self.w_quant(self.inner.weight)
        from ..nn import functional as F
        c = self.inner
        return F.conv2d(x, w, c.bias, c.stride, c.padding, c.dilation,
                        c.groups, c.data_format)


class ImperativeQuantAware:
    """QAT transformer (~ slim/quantization/imperative/qat.py:104):
    swaps Linear/Conv2D sublayers for fake-quantized versions."""

    def __init__(self, bits=8, quantizable_layer_type=("Linear", "Conv2D")):
        self.bits = bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model: nn.Layer) -> nn.Layer:
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            if cls == "Linear" and "Linear" in self.types:
                model._sub_layers[name] = QuantedLinear(sub, self.bits)
            elif cls == "Conv2D" and "Conv2D" in self.types:
                model._sub_layers[name] = QuantedConv2D(sub, self.bits)
            else:
                self.quantize(sub)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ calibration (~ post_training_quantization.py:229).

    Runs calibration batches with forward pre-hooks observing every
    quantizable layer's input through the chosen algorithm (abs_max / avg
    / hist / KL, reference `algo` arg), then freezes the model to int8
    execution (per-channel int8 weights + static activation scales,
    quantization/int8.py — the QuantizationFreezePass analog).
    """

    def __init__(self, model: nn.Layer, data_loader, bits=8,
                 algo="abs_max", quantizable_layer_type=("Linear",)):
        assert bits == 8, "int8 is the TPU-native quantized width"
        self.model = model
        self.loader = data_loader
        self.bits = bits
        self.algo = algo
        self.types = set(quantizable_layer_type)
        self.act_scales: dict[str, float] = {}

    def _observed_layers(self):
        # "Linear" also covers the model-parallel Linears, which
        # convert_to_int8 quantizes — calibration must observe every
        # layer the conversion will touch or they'd silently fall back
        # to dynamic activation scales
        aliases = {"ColumnParallelLinear": "Linear",
                   "RowParallelLinear": "Linear"}
        for name, layer in self.model.named_sublayers():
            cls = type(layer).__name__
            if cls in self.types or aliases.get(cls) in self.types:
                yield name, layer

    def quantize(self) -> nn.Layer:
        observers = {}
        hooks = []
        for name, layer in self._observed_layers():
            obs = make_observer(self.algo)
            observers[name] = obs

            def pre_hook(lyr, inputs, _obs=obs):
                x = inputs[0]
                _obs.update(np.asarray(
                    x._value if isinstance(x, Tensor) else x))
                return inputs

            hooks.append(layer.register_forward_pre_hook(pre_hook))
        self.model.eval()
        from ..autograd import no_grad
        with no_grad():
            for batch in self.loader:
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                if not isinstance(x, Tensor):
                    x = Tensor(jnp.asarray(x))
                self.model(x)
        for h in hooks:
            h.remove()
        from .int8 import QMAX
        self.act_scales = {name: obs.scale() / QMAX
                           for name, obs in observers.items()}
        return convert_to_int8(self.model, self.act_scales)

    def save_quantized_model(self, save_model_path, **kw):
        from ..framework.io import save
        state = {}
        for name, layer in self.model.named_sublayers():
            if isinstance(layer, Int8Linear):
                state[f"{name}.weight_int8"] = np.asarray(
                    layer.weight_q._value)
                state[f"{name}.weight_scale"] = np.asarray(
                    layer.weight_scale._value)
                if layer.act_scale is not None:
                    state[f"{name}.act_scale"] = float(layer.act_scale)
        save(state, save_model_path + ".pdquant")
        return state
