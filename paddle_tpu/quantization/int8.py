"""Int8 execution layers — the deploy artifact of quantization.

~ the reference's quantized inference path (slim QuantizationFreezePass +
int8 cuDNN/mkldnn kernels): after PTQ/QAT, Linear weights are stored as
int8 with per-output-channel scales and the matmul runs in int8 with an
int32 accumulator — on TPU this hits the MXU's native int8 path
(lax.dot_general with preferred_element_type=int32), giving 2x the bf16
peak on v5e-class chips.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor

QMAX = 127


def quantize_weight_per_channel(w: np.ndarray, axis: int = 1):
    """int8 per-output-channel symmetric quantization.

    Returns (q_int8, scales) with scales shaped to broadcast along
    ``axis`` (the output-feature axis; 1 for (in, out) Linear weights).
    ~ fake_channel_wise_quantize_dequantize_abs_max.
    """
    w = np.asarray(w, np.float32)
    red = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.maximum(np.abs(w).max(axis=red, keepdims=True), 1e-8)
    q = np.clip(np.round(w / amax * QMAX), -QMAX, QMAX).astype(np.int8)
    return q, (amax / QMAX).astype(np.float32)


def quantize_stacked_jnp(w):
    """jnp variant for (..., in, out) (possibly layer-stacked) weights:
    per-output-channel scales over the 'in' axis. Returns
    (q int8, scale f32 with the 'in' axis reduced away)."""
    wf = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2), 1e-8) / QMAX
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -QMAX, QMAX)
    return q.astype(jnp.int8), scale


def int8_matmul(x, wq, scale, act_scale=None):
    """x (..., in) @ wq (in, out) int8; activation scale is calibrated
    (``act_scale``) or dynamic per-tensor abs-max. Accumulates int32 on
    the MXU, rescales to x.dtype. The shared int8 GEMM used by
    Int8Linear and the compiled decode."""
    xf = x.astype(jnp.float32)
    if act_scale is None:
        sx = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-8) / QMAX
    else:
        sx = jnp.asarray(act_scale, jnp.float32)
    xq = jnp.clip(jnp.round(xf / sx), -QMAX, QMAX).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * (sx * scale)).astype(x.dtype)


class Int8Linear(nn.Layer):
    """Linear with frozen int8 weights + dynamic int8 activations.

    Activation scale comes from calibration (static, preferred) or from
    the runtime abs-max when none was recorded (dynamic quantization).
    """

    def __init__(self, linear: nn.Linear, act_scale: float | None = None):
        super().__init__()
        q, w_scale = quantize_weight_per_channel(
            np.asarray(linear.weight._value), axis=1)
        self.register_buffer("weight_q", Tensor(jnp.asarray(q)))
        self.register_buffer("weight_scale", Tensor(jnp.asarray(w_scale)))
        self.bias = linear.bias
        self.act_scale = act_scale
        self.in_features = linear.in_features
        self.out_features = linear.out_features

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        # f32 in -> shared GEMM returns f32; bias adds in f32 before the
        # downcast to the caller's dtype
        out = int8_matmul(xv.astype(jnp.float32), self.weight_q._value,
                          self.weight_scale._value[0], self.act_scale)
        if self.bias is not None:
            out = out + self.bias._value
        return Tensor(out.astype(xv.dtype))


def convert_to_int8(model: nn.Layer, act_scales: dict | None = None,
                    prefix: str = "") -> nn.Layer:
    """Swap Linear sublayers for Int8Linear (~ QuantizationFreezePass).

    act_scales maps sublayer path -> calibrated activation scale; layers
    without an entry fall back to dynamic activation quantization.
    """
    # model-parallel Linears are Linear-shaped (weight (in,out) + bias)
    # and quantize the same way; their sharding annotations carry over to
    # the int8 buffers (scales follow the out-channel axis), so the MP
    # memory sharding survives conversion. Imported here to avoid a
    # quantization<->distributed import cycle.
    from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers \
        import ColumnParallelLinear, RowParallelLinear
    quantizable = (nn.Linear, ColumnParallelLinear, RowParallelLinear)
    act_scales = act_scales or {}
    for name, sub in list(model._sub_layers.items()):
        path = f"{prefix}.{name}" if prefix else name
        if isinstance(sub, quantizable):
            q = Int8Linear(sub, act_scale=act_scales.get(path))
            spec = getattr(sub.weight, "sharding_spec", None)
            if spec is not None:
                from jax.sharding import PartitionSpec as P
                q.weight_q.sharding_spec = spec
                # weight_scale is (1, out): axis 1 follows the weight's
                # out-channel placement
                q.weight_scale.sharding_spec = P(None, spec[1])
            model._sub_layers[name] = q
        else:
            convert_to_int8(sub, act_scales, path)
    return model
