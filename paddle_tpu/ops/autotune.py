"""Runtime kernel autotuning with a persistent cache.

~ paddle/phi/kernels/autotune/ (AutoTuneBase auto_tune_base.h:48: time every
candidate once, pick the fastest; AutoTuneCache cache.h:144 keyed by op +
shape/dtype signature; switch_autotune.cc flag gating).

TPU shape: candidates are whole jitted callables (e.g. a Pallas kernel at
several block sizes) — each is compiled + timed on the real arguments the
first time a (op, signature) key is seen; the winner is cached for the
process and exportable/importable like the reference's cache file.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Sequence

import jax

from ..core import flags as _flags

_flags.define_flag("use_autotune", False, "enable runtime kernel autotune")


class AutoTuneCache:
    """(op, signature) -> chosen candidate index (+ timings for report)."""

    def __init__(self):
        self._cache: Dict[tuple, int] = {}
        self._timings: Dict[tuple, list] = {}
        self.hits = 0
        self.misses = 0

    def key(self, op: str, args, tag: str = "") -> tuple:
        """tag fingerprints the candidate list: persisted entries store a
        bare index, so a reordered/extended candidate set must produce a
        different key (stale imported entries are then simply unmatched)."""
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in args
                    if hasattr(a, "shape"))
        return (op, sig, tag)

    def get(self, key):
        if key in self._cache:
            self.hits += 1
            return self._cache[key]
        self.misses += 1
        return None

    def peek(self, key):
        """Lookup without touching the hit/miss statistics (for passive
        probes like the jit-trace path that never trigger a tune)."""
        return self._cache.get(key)

    def put(self, key, idx, timings=None):
        self._cache[key] = idx
        if timings is not None:
            self._timings[key] = timings

    def report(self):
        return {"hits": self.hits, "misses": self.misses,
                "size": len(self._cache)}

    def export(self, path: str):
        payload = {json.dumps(list(k)): v for k, v in self._cache.items()}
        with open(path, "w") as f:
            json.dump(payload, f)

    def load(self, path: str):
        def canon(x):
            return (tuple(canon(i) for i in x) if isinstance(x, list)
                    else x)

        with open(path) as f:
            payload = json.load(f)
        for k, v in payload.items():
            parts = json.loads(k)
            op, sig = parts[0], canon(parts[1])
            tag = parts[2] if len(parts) > 2 else ""
            self._cache[(op, sig, tag)] = v


_CACHE = AutoTuneCache()


def cache() -> AutoTuneCache:
    return _CACHE


def enable_autotune():
    _flags.set_flags({"use_autotune": True})


def disable_autotune():
    _flags.set_flags({"use_autotune": False})


def autotune_enabled() -> bool:
    return bool(_flags.get_flag("use_autotune"))


def _sync(out) -> None:
    """Force real device synchronization (block_until_ready is not a real
    barrier on remote-tunneled platforms — see core/sync.py)."""
    from ..core.sync import hard_sync
    hard_sync(out)


def _time_once(fn: Callable, args, warmup: int = 1, iters: int = 3) -> float:
    try:
        for _ in range(warmup):
            out = fn(*args)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        return (time.perf_counter() - t0) / iters
    except Exception:
        return float("inf")


def autotune(op: str, candidates: Sequence[Callable], args,
             default: int = 0, tag: str = "") -> Callable:
    """Pick the fastest candidate for these argument shapes.

    Off (the default, like FLAGS_use_autotune): returns candidates[default].
    On: first call per (op, signature) times each candidate on the real
    args; later calls hit the cache. Pass a `tag` identifying the candidate
    set so persisted indices never dereference a different list.
    """
    if not autotune_enabled() or len(candidates) == 1:
        return candidates[default]
    key = _CACHE.key(op, args, tag)
    idx = _CACHE.get(key)
    if idx is not None:
        return candidates[idx]
    timings = [_time_once(c, args) for c in candidates]
    best = min(range(len(timings)), key=timings.__getitem__)
    if timings[best] == float("inf"):
        best = default
    _CACHE.put(key, best, timings)
    return candidates[best]


# ---- tuned flash attention -------------------------------------------------

# The canonical measured best-first ordering lives next to the kernels;
# sharing it keeps the tuner's candidate order and the resolver's
# auto-pick from ever diverging.
from .pallas.flash_attention import MEASURED_BLOCK_ORDER as _FA_BLOCKS


def tuned_flash_attention(q, k, v, causal=False, sm_scale=None):
    """Flash attention with autotuned (block_q, block_k).

    Candidates are block configs that divide the sequence lengths. Timing
    happens only on concrete (eager) calls; under a jit trace the cached
    choice for this signature is used (falling back to the default blocks),
    so the tune is race-free with compilation."""
    from .pallas.flash_attention import flash_attention
    Sq, Sk = q.shape[2], k.shape[2]
    configs = [(bq, bk) for bq, bk in _FA_BLOCKS
               if Sq % bq == 0 and Sk % bk == 0]
    if not configs:
        configs = [(None, None)]  # auto-pick divisor blocks in the kernel

    def make(bq, bk):
        def run(q_, k_, v_):
            return flash_attention(q_, k_, v_, causal, sm_scale, bq, bk)
        return run

    cands = [make(bq, bk) for bq, bk in configs]
    tag = str(configs)
    if isinstance(q, jax.core.Tracer):
        idx = _CACHE.peek(
            _CACHE.key("flash_attention", (q, k, v), tag)) or 0
        return cands[idx](q, k, v)
    chosen = autotune("flash_attention", cands, (q, k, v), tag=tag)
    return chosen(q, k, v)
