"""Structured control-flow ops.

~ the reference's controlflow operators (paddle/fluid/operators/controlflow/
conditional_block_op.cc, while_op.cc) and paddle.static.nn.cond/while_loop.
On TPU these ARE the dy2static story: data-dependent control flow inside
jit must be lax.cond/while_loop/scan; eagerly they just execute.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .dispatch import apply_op


def _unwrap(tree):
    return jax.tree.map(lambda x: x._value if isinstance(x, Tensor) else x,
                        tree, is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(tree):
    return jax.tree.map(lambda x: Tensor(x) if isinstance(x, jax.Array)
                        else x, tree)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """~ paddle.static.nn.cond / lax.cond hybrid.

    Eager (concrete pred): runs the chosen branch directly — autograd tape
    records through it. Traced (pred is a tracer): lowers to lax.cond.
    """
    pv = pred._value if isinstance(pred, Tensor) else pred
    if isinstance(pv, jax.core.Tracer):
        ops_v = _unwrap(operands)

        def tf(ops):
            return _unwrap(true_fn(*_wrap(ops)))

        def ff(ops):
            return _unwrap(false_fn(*_wrap(ops)))
        return _wrap(jax.lax.cond(pv, tf, ff, ops_v))
    if bool(pv):
        return true_fn(*operands)
    return false_fn(*operands)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence):
    """~ paddle.static.nn.while_loop (fluid/layers/control_flow.py).

    Eager: python loop (tape-recorded). Traced: lax.while_loop with shape
    invariants enforced by jax.
    """
    loop_vars = list(loop_vars)
    vals = _unwrap(loop_vars)
    leaves = jax.tree.leaves(vals)
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        def cf(vs):
            out = cond_fn(*_wrap(vs))
            return out._value if isinstance(out, Tensor) else out

        def bf(vs):
            return _unwrap(list(body_fn(*_wrap(vs))))
        return _wrap(jax.lax.while_loop(cf, bf, vals))
    while bool(_unwrap(cond_fn(*loop_vars))
               if isinstance(cond_fn(*loop_vars), Tensor)
               else cond_fn(*loop_vars)):
        loop_vars = list(body_fn(*loop_vars))
    return loop_vars


def scan(body_fn: Callable, init, xs, length=None):
    """jax-native scan surfaced at the framework level (no direct reference
    analog — the TPU-idiomatic replacement for unrolled RNN loops)."""
    init_v = _unwrap(init)
    xs_v = _unwrap(xs)

    def bf(carry, x):
        c, y = body_fn(_wrap(carry), _wrap(x))
        return _unwrap(c), _unwrap(y)
    carry, ys = jax.lax.scan(bf, init_v, xs_v, length=length)
    return _wrap(carry), _wrap(ys)


def case(pred_fn_pairs, default=None):
    """~ paddle.static.nn.case."""
    for pred, fn in pred_fn_pairs:
        pv = pred._value if isinstance(pred, Tensor) else pred
        if bool(pv):
            return fn()
    if default is not None:
        return default()
    raise ValueError("no branch taken and no default given")


def switch_case(branch_index, branch_fns, default=None):
    """~ paddle.static.nn.switch_case; lowers to lax.switch when traced."""
    iv = branch_index._value if isinstance(branch_index, Tensor) \
        else branch_index
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        index_map = {k: i for i, k in enumerate(keys)}
    else:
        fns = list(branch_fns)
        index_map = None
    if isinstance(iv, jax.core.Tracer):
        def mk(fn):
            return lambda _: _unwrap(fn())
        return _wrap(jax.lax.switch(jnp.clip(iv, 0, len(fns) - 1),
                                    [mk(f) for f in fns], 0))
    i = int(iv)
    if index_map is not None:
        i = index_map.get(i, None)
        if i is None:
            if default is not None:
                return default()
            raise ValueError(f"branch {iv} not found")
    if 0 <= i < len(fns):
        return fns[i]()
    if default is not None:
        return default()
    raise IndexError(f"branch index {i} out of range")
