"""Paged KV-cache decode attention — the vLLM-style serving kernel.

The reference's generative path (fused_multi_transformer_op.cu) allocates
a DENSE (B, H, max_len, D) cache per batch slot: memory scales with
max_len whatever the actual lengths, and sequences cannot share a pool.
Paged attention stores K/V in fixed-size PAGES drawn from one global
pool; each sequence holds a page table of indices, so cache memory
tracks the sum of real lengths and slots are reused across requests —
the design that makes continuous batching work.

TPU mapping: the page table rides the scalar-prefetch channel
(pltpu.PrefetchScalarGridSpec) so the BlockSpec index_map can address
the NEXT page's (page_size, D) K/V block in HBM while the current one
computes — Pallas double-buffers the gather; the kernel itself is an
online-softmax accumulation over the grid's page axis with VMEM scratch
carrying (m, l, acc) between pages. GQA: all G query heads sharing a kv
head run in one program, so each page is fetched ONCE per kv head.

API:
  paged_attention(q, k_pages, v_pages, page_tables, seq_lens)
    q           (B, Hq, D)            one decode position per sequence
    k/v_pages   (Hkv, P, page_size, D) global page pools
    page_tables (B, pages_per_seq)    page ids (padding ids are masked)
    seq_lens    (B,)                  real lengths -> (B, Hq, D)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...jax_compat import tpu_compiler_params
from ...obs import ledger as obs_ledger

# jax renamed TPUCompilerParams -> CompilerParams (version-bridged in
# one place, jax_compat)
_CompilerParams = tpu_compiler_params()

from .flash_attention import NEG_INF, _interpret


def _paged_kernel(st_ref, pt_ref, sl_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale, page_size, chunk, quantized=False):
    """ONE program per (sequence, kv head, page), shared by decode and
    chunked prefill: (G*chunk) query rows accumulate online softmax over
    the page axis with VMEM scratch. Row r sits at absolute position
    st_ref[b] + (r % chunk); masking is causal over absolute positions
    AND bounded by seq_len — decode is simply the chunk=1 case with
    st = seq_len - 1. ``quantized``: int8 K/V refs with two per-slot f32
    scale refs preceding the output; dequant happens here in VMEM.
    Pages entirely beyond the causal horizon or the sequence length are
    skipped (no dot/exp), though their DMA is already pipelined."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    seq_len = sl_ref[b]
    start = st_ref[b]
    base = j * page_size
    live = (base <= start + chunk - 1) & (base < seq_len)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)        # (G*chunk, D)
        k = k_ref[0, 0].astype(jnp.float32)        # (page_size, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            kk = k * ks_ref[0, 0]
            vv = v * vs_ref[0, 0]
        else:
            kk, vv = k, v
        s = jax.lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                           # (G*chunk, page_size)
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        row_pos = start + jax.lax.rem(rows, chunk)
        col_pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (col_pos <= row_pos) & (col_pos < seq_len)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _done():
        o_ref[0, 0] = (acc_scr[...]
                       / jnp.maximum(l_scr[...], 1e-20)).astype(
            o_ref.dtype)


def _paged_call(q4, k_pages, v_pages, page_tables, seq_lens, starts,
                chunk, sm_scale, k_scales, v_scales):
    """Shared launcher: q4 (B, Hkv, G*chunk, D) -> same shape out."""
    B, Hkv, rows, D = q4.shape
    _, P, page_size, Dk = k_pages.shape
    if D != Dk:
        raise ValueError(f"head_dim mismatch: q {D} vs pages {Dk}")
    n_pages = page_tables.shape[1]
    quantized = k_scales is not None or v_scales is not None
    if quantized and (k_scales is None or v_scales is None):
        raise ValueError("int8 pools need BOTH k_scales and v_scales")

    q_spec = pl.BlockSpec((1, 1, rows, D), lambda b, h, j, st, pt, sl:
                          (b, h, 0, 0))
    page_spec = pl.BlockSpec((1, 1, page_size, D),
                             lambda b, h, j, st, pt, sl:
                             (h, pt[b, j], 0, 0))
    scale_spec = pl.BlockSpec((1, 1, page_size, 1),
                              lambda b, h, j, st, pt, sl:
                              (h, pt[b, j], 0, 0))
    in_specs = [q_spec, page_spec, page_spec]
    args = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        args += [k_scales[..., None].astype(jnp.float32),
                 v_scales[..., None].astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hkv, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, D),
                               lambda b, h, j, st, pt, sl: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, sm_scale=sm_scale,
                          page_size=page_size, chunk=chunk,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rows, D), q4.dtype),
        interpret=_interpret(),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(jnp.asarray(starts, jnp.int32).reshape(B),
      jnp.asarray(page_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32), *args)


def paged_attention(q, k_pages, v_pages, page_tables, seq_lens,
                    sm_scale=None, k_scales=None, v_scales=None):
    """Decode-step attention over a paged KV pool (shapes in the module
    docstring). ``k_scales``/``v_scales`` (Hkv, P, page_size) switch the
    int8-pool path: pages are int8 and dequantized in VMEM per block.
    Non-differentiable by design — a serving kernel. Internally the
    chunk=1 case of the shared paged kernel with start = seq_len - 1."""
    B, Hq, D = q.shape
    Hkv = k_pages.shape[0]
    if Hq % Hkv:
        raise ValueError(f"query heads {Hq} not a multiple of kv heads "
                         f"{Hkv}")
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    sl = jnp.asarray(seq_lens, jnp.int32)
    out = _paged_call(q.reshape(B, Hkv, G, D), k_pages, v_pages,
                      page_tables, sl, jnp.maximum(sl - 1, 0), 1,
                      sm_scale, k_scales, v_scales)
    return out.reshape(B, Hq, D)


def paged_attention_reference(q, k_pages, v_pages, page_tables, seq_lens,
                              sm_scale=None):
    """Dense jnp oracle (gathers pages, masks, exact softmax)."""
    B, Hq, D = q.shape
    Hkv, P, page_size, _ = k_pages.shape
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    n_pages = page_tables.shape[1]
    S = n_pages * page_size
    # (B, Hkv, S, D) gathered caches
    k = k_pages[:, page_tables].transpose(1, 0, 2, 3, 4).reshape(
        B, Hkv, S, D)
    v = v_pages[:, page_tables].transpose(1, 0, 2, 3, 4).reshape(
        B, Hkv, S, D)
    qg = q.reshape(B, Hkv, G, D).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhsd->bhgs", qg,
                   k.astype(jnp.float32)) * sm_scale
    mask = jnp.arange(S)[None, :] < jnp.asarray(seq_lens)[:, None]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhgs,bhsd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(B, Hq, D).astype(q.dtype)


class PagedKVCache:
    """Host-side page-pool bookkeeping for serving loops: a free list of
    pages plus per-sequence tables (~ vLLM's BlockManager). Device data
    stays functional — ``write`` returns the updated pools."""

    def __init__(self, n_pages: int, page_size: int, kv_heads: int,
                 head_dim: int, dtype=jnp.bfloat16):
        self.page_size = page_size
        self.k_pages = jnp.zeros((kv_heads, n_pages, page_size, head_dim),
                                 dtype)
        self.v_pages = jnp.zeros_like(self.k_pages)
        self._free = list(range(n_pages - 1, 0, -1))  # page 0 = padding
        self.tables: dict = {}
        self.lengths: dict = {}
        # prefix cache (~ vLLM automatic prefix caching / SGLang
        # RadixAttention, flattened to exact-match chain hashing): FULL
        # pages of identical token prefixes are shared across sequences.
        # Key = (parent_page_or_0, tuple of page tokens) -> page id;
        # refcounts keep shared pages alive while anyone holds them.
        # RETENTION: a published page whose refcount hits 0 does NOT
        # return to the free list — it parks in the evictable LRU pool,
        # key intact, so a later identical prefix revives it for free.
        # allocate() reclaims from the LRU leaf-first only once the
        # free list runs dry (a parent page never dies before its
        # children — the chain invariant that keeps recycled page ids
        # from ever matching stale child keys).
        self._prefix: dict = {}
        self._refs: dict = {}       # page id -> holders (resident set)
        self._page_key: dict = {}   # page id -> its prefix key
        self._children: dict = {}   # page id -> keys with it as parent
        self._evictable: dict = {}  # page id -> True; insertion = LRU
        self._stats = {"hit_tokens": 0, "lookup_tokens": 0,
                       "evictions": 0, "compactions": 0}
        # quantized-tier overlay (kv_quant serving): page ids whose
        # device content is stored int8+scale. Strictly a subset of
        # resident|evictable — the resident+evictable+free census is
        # untouched; a page's tier dies with its id (eviction, an
        # unpublished free, purge) so a recycled id never reads stale
        # int8 data.
        self._quant: set = set()
        self._kv_quant: str | None = None
        self._page_bytes: tuple | None = None  # (fp, int8+scale) /page
        self._byte_budget: int | None = None
        self._compact_cb = None
        # host-DRAM offload tier (hostmem serving): pages the eviction
        # scan would recycle spill their content to a byte-budgeted
        # HostArena instead of dying, keyed by FULL token prefix
        # (root..page) so a spilled chain's identity survives device
        # page-id recycling. _spilled maps that key -> True for every
        # page this bookkeeper parked in the arena; spill/page-in data
        # movement is the engine's (the _spill_cb / page_in import_cb
        # closures price it on the virtual clock — this bookkeeper
        # never touches device arrays). None/empty when unarmed: the
        # resident+evictable+free census and every stat dict stay
        # byte-identical to the pre-hostmem engine.
        self._arena = None
        self._spill_cb = None
        self._host_page_bytes: tuple | None = None  # (fp, q) /page
        self._spilled: dict = {}
        self._spill_stats = {"spills": 0, "pageins": 0,
                             "spill_refusals": 0}
        # pool generation: purge() bumps it. Content written under an
        # earlier epoch is unreachable after a purge (every key dropped,
        # every page back on the free list), so a restarted replica
        # over this bookkeeper can never serve pre-crash pages; the
        # tag makes "which generation is this pool" checkable.
        self.epoch = 0
        # per-device pool residency, NOTED by the serving engine when
        # its factory pools are mesh-sharded (this bookkeeper's own
        # arrays are 1-element stand-ins there); None = never noted,
        # and cache_stats stays byte-identical to the unsharded shape
        self._pool_bytes: tuple | None = None

    def note_pool_bytes(self, total_bytes: int,
                        per_device_bytes: int | None = None):
        """Record the REAL pool's byte footprint (the serving factory
        owns the device arrays; this bookkeeper owns the accounting):
        ``cache_stats()`` then reports ``bytes_per_device`` — the
        number the tensor-parallel capacity claims are gated on. With
        ``per_device_bytes`` omitted the pool is unsharded (one device
        holds everything)."""
        total = int(total_bytes)
        self._pool_bytes = (total, int(per_device_bytes)
                            if per_device_bytes is not None else total)

    # --- quantized page tier (kv_quant serving) ------------------------

    def note_kv_quant(self, mode: str, fp_bytes_per_page: int | None = None,
                      q_bytes_per_page: int | None = None,
                      byte_budget: int | None = None, compact_cb=None):
        """Arm the quantized-tier accounting. ``mode`` is ``"int8"``
        (every occupied page already stored int8+scale by the factory)
        or ``"pressure"`` (full-precision hot pages; parked pages are
        compacted to int8 under byte pressure). The per-page byte costs
        let ``stored_bytes()`` price the pool as actually stored;
        ``byte_budget`` (pressure only) makes ``allocate()`` reclaim
        bytes by compacting the evictable LRU — oldest first, prefix
        keys intact — BEFORE giving up with MemoryError.
        ``compact_cb(page_ids)`` is the device-side compaction hook the
        engine installs (this bookkeeper never touches device data)."""
        if mode not in ("int8", "pressure"):
            raise ValueError(f"note_kv_quant: unknown mode {mode!r}")
        self._kv_quant = mode
        if fp_bytes_per_page is not None:
            self._page_bytes = (int(fp_bytes_per_page),
                                int(q_bytes_per_page))
        self._byte_budget = int(byte_budget) \
            if byte_budget is not None else None
        self._compact_cb = compact_cb

    def quantized_pages(self) -> set:
        return set(self._quant)

    def mark_quantized(self, page_ids):
        """Record that ``page_ids`` are now stored int8 (e.g. after a
        disaggregated import of a mixed-tier chain). Pages must be
        occupied — a free page has no content to have a tier."""
        for p in page_ids:
            if p not in self._refs and p not in self._evictable:
                raise ValueError(
                    f"mark_quantized: page {p} is not occupied")
            self._quant.add(p)

    def compact_candidates(self):
        """Evictable pages not yet quantized, oldest-parked first —
        the order compaction spends them (mirrors the eviction LRU,
        except nothing is forgotten: keys and census stay intact)."""
        return [p for p in self._evictable if p not in self._quant]

    def compact_evictable(self, max_pages: int | None = None) -> list:
        """Compact up to ``max_pages`` (default: all) evictable
        full-precision pages to int8, oldest first: the device hook
        runs first (so a failure there leaves the tier unmarked), then
        the pages join the quantized tier. Returns the page ids
        compacted. Census is untouched — the pages stay evictable,
        keys live, revivable by the same prefixes."""
        cands = self.compact_candidates()
        if max_pages is not None:
            cands = cands[:max_pages]
        if cands:
            if self._compact_cb is not None:
                self._compact_cb(list(cands))
            self._quant.update(cands)
            self._stats["compactions"] += len(cands)
        return cands

    def stored_bytes(self) -> int | None:
        """Bytes the OCCUPIED pages (resident + evictable) actually
        cost as stored: quantized pages at int8+scale size, the rest
        at full precision. None until note_kv_quant supplied per-page
        costs. This is the dynamic pressure signal — admission grows
        it, compaction shrinks it, eviction zeroes a page's share."""
        if self._page_bytes is None:
            return None
        fp, q = self._page_bytes
        occupied = len(self._refs) + len(self._evictable)
        n_q = len(self._quant)
        if self._kv_quant == "int8":
            return occupied * q
        return (occupied - n_q) * fp + n_q * q

    # --- host-DRAM offload tier (hostmem serving) ----------------------

    def note_hostmem(self, arena, spill_cb,
                     fp_bytes_per_page: int,
                     q_bytes_per_page: int | None = None):
        """Arm the host-arena spill tier. ``arena`` is a
        ``serving.hostmem.HostArena``; ``spill_cb(page_id, quant)``
        is the engine's export closure — it copies the page's device
        content host-side (priced as one ``kv_pageout`` on the
        virtual clock) and returns the opaque data blob the arena
        stores. Per-page byte prices charge the arena budget: a page
        sitting in the int8 tier spills at ``q_bytes_per_page``
        (the kv_quant_page_bytes arithmetic carried across the tier
        boundary), everything else at ``fp_bytes_per_page``."""
        self._arena = arena
        self._spill_cb = spill_cb
        q = int(q_bytes_per_page) if q_bytes_per_page is not None \
            else int(fp_bytes_per_page)
        self._host_page_bytes = (int(fp_bytes_per_page), q)

    def _spill_key(self, p) -> tuple | None:
        """Page ``p``'s FULL token prefix (root..p, a page multiple of
        tokens), reconstructed by walking parent keys — the identity a
        spilled page keeps after its device id recycles. None for an
        unpublished page or a broken walk (nothing to spill under)."""
        parts = []
        while p != 0:
            key = self._page_key.get(p)
            if key is None:
                return None
            parts.append(key[1])
            p = key[0]
        toks: tuple = ()
        for seg in reversed(parts):
            toks += seg
        return toks

    def _try_spill(self, p):
        """Park evicted page ``p``'s content in the host arena before
        its device id recycles. Refusal (arena budget exhausted, or an
        unpublished page with no prefix identity) is silent: the page
        simply dies exactly as it did pre-hostmem. A key the arena
        already holds is NOT re-copied — same token prefix, same K/V
        content."""
        key = self._spill_key(p)
        if key is None:
            return
        if key in self._spilled:
            if key in self._arena:
                return  # identical content already parked host-side
            del self._spilled[key]  # arena LRU reclaimed it since —
            # fall through and re-spill the fresh copy
        quant = p in self._quant
        fp, q = self._host_page_bytes
        try:
            data = self._spill_cb(p, quant)
            self._arena.put(key, data, q if quant else fp,
                            quant=quant, epoch=self.epoch)
        except MemoryError:
            self._spill_stats["spill_refusals"] += 1
            return
        self._spilled[key] = True
        self._spill_stats["spills"] += 1

    def _prune_spilled(self):
        """Drop bookkeeping for keys the arena's own LRU reclaimed
        behind our back (the arena owes the bookkeeper no callback;
        reconciliation is lazy, before any read of ``_spilled``)."""
        gone = [k for k in self._spilled if k not in self._arena]
        for k in gone:
            del self._spilled[k]

    def spilled_extension(self, tokens, start: int) -> list:
        """The spilled keys that would EXTEND ``tokens``' resident
        chain past ``start`` cached tokens (a page multiple), in chain
        order — the admission probe for a priced page-in. Stops at the
        first hole: a mid-chain gap means the earlier pages' K/V is
        gone and everything past it would be wrong-context."""
        out = []
        n = int(start)
        ps = self.page_size
        while n + ps <= len(tokens):
            key = tuple(int(t) for t in tokens[:n + ps])
            if key not in self._spilled \
                    or self._arena.peek(key) is None:
                break
            out.append(key)
            n += ps
        return out

    def page_in(self, seq_id, tokens, start: int, import_cb) -> int:
        """Restore the spilled extension of ``tokens[:start]`` into
        ``seq_id``'s chain: per spilled page, take one device page
        (free list first, eviction — which may itself spill — when
        dry), hand it to ``import_cb(page_id, entry)`` (the engine's
        scatter closure, priced as one ``kv_pagein``), then publish it
        resident under ``seq_id`` exactly as if a prefill had written
        and registered it. Stops — cleanly, partial restores are
        valid prefixes — when the pool cannot yield a page. Returns
        tokens paged in (``lengths[seq_id]`` advanced past them, so
        the prefill resumes beyond the restored prefix). Call between
        ``acquire_prefix`` and ``allocate``; ``rollback_acquire``
        stays exact because restored tokens are counted as hits."""
        table = self.tables.get(seq_id)
        if table is None:
            raise KeyError(f"page_in: unknown sequence {seq_id!r}")
        ps = self.page_size
        n = int(start)
        restored = 0
        for key in self.spilled_extension(tokens, n):
            if not self._free and not self._evictable:
                break
            if not self._free:
                self._evict_lru()  # may itself SPILL, which may evict
                # arena LRU entries — re-probe the key below
            if not self._free:
                break
            entry = self._arena.peek(key)
            if entry is None or entry.epoch != self.epoch:
                break  # evicted arena-side just now, or pre-purge
                # content that must never serve
            p = self._free.pop()
            entry = self._arena.take(key)
            self._spilled.pop(key, None)
            import_cb(p, entry)
            self._refs[p] = 1
            table.append(p)
            parent = table[-2] if len(table) >= 2 else 0
            pkey = (parent, key[n:n + ps])
            self._prefix[pkey] = p
            self._page_key[p] = pkey
            self._children.setdefault(parent, set()).add(pkey)
            if entry.quant:
                self._quant.add(p)
            n += ps
            restored += ps
            self._spill_stats["pageins"] += 1
        if restored:
            self._stats["hit_tokens"] += restored
            self.lengths[seq_id] = n
        return restored

    def spill_chain(self, seq_id, tokens, owner: str) -> list:
        """Preemption-as-swap: park ``seq_id``'s live chain content in
        the arena PINNED under ``owner`` (the rid — a preempted
        request's only K/V copy must survive arbitrary spill traffic
        until it pages back in). Spills every FULL page covered by
        ``lengths[seq_id]`` positions of ``tokens`` (prompt + emitted
        history; the trailing partial page re-prefills on resume).
        ALL-OR-NOTHING: if the arena refuses any page, every put/pin
        this call made is rolled back and [] returns — the caller
        then declines to preempt. Returns the pinned keys on success.
        Pages stay allocated; the caller frees the sequence after."""
        table = self.tables.get(seq_id)
        if table is None:
            raise KeyError(f"spill_chain: unknown sequence "
                           f"{seq_id!r}")
        ps = self.page_size
        n_full = min(int(self.lengths.get(seq_id, 0)) // ps,
                     len(table))
        fp, q = self._host_page_bytes
        put_keys, pinned_keys = [], []
        try:
            for i in range(n_full):
                key = tuple(int(t) for t in tokens[:(i + 1) * ps])
                p = table[i]
                quant = p in self._quant
                if key in self._spilled:
                    e = self._arena.peek(key)
                    if e is not None and e.owner is None:
                        self._arena.pin(key, owner)
                        pinned_keys.append(key)
                    continue  # already parked (or pinned elsewhere —
                    # equally protected); content is identical
                data = self._spill_cb(p, quant)
                self._arena.put(key, data, q if quant else fp,
                                quant=quant, epoch=self.epoch,
                                pin=owner)
                self._spilled[key] = True
                self._spill_stats["spills"] += 1
                put_keys.append(key)
        except MemoryError:
            self._spill_stats["spill_refusals"] += 1
            for key in put_keys:
                self._arena.drop(key)
                self._spilled.pop(key, None)
                self._spill_stats["spills"] -= 1
            for key in pinned_keys:
                self._arena.unpin(key)
            return []
        return put_keys + pinned_keys

    def drop_spilled_owner(self, owner: str) -> int:
        """A preempted request was shed while requeued: its pinned
        chain will never page back in — release the arena bytes and
        forget the keys. Returns entries dropped."""
        dropped = [k for k in list(self._spilled)
                   if (e := self._arena.peek(k)) is not None
                   and e.owner == owner]
        for k in dropped:
            self._arena.drop(k)
            del self._spilled[k]
        return len(dropped)

    def unpin_spilled_owner(self, owner: str):
        """Demote ``owner``'s still-pinned keys to the arena LRU (a
        restored request consumed the keys it needed; leftovers —
        shared-prefix pages that matched resident instead — go back
        to being ordinary spilled cache)."""
        for k in list(self._spilled):
            e = self._arena.peek(k)
            if e is not None and e.owner == owner:
                self._arena.unpin(k)

    def allocate(self, seq_id, n_tokens: int):
        """Reserve pages so ``seq_id`` can hold n_tokens total. The
        free list is spent first; evictable LRU pages are reclaimed
        leaf-first only when it dries. MemoryError fires only when
        free + evictable together cannot cover the need (and mutates
        nothing, so a caller can free()/requeue safely)."""
        table = self.tables.setdefault(seq_id, [])
        need = -(-n_tokens // self.page_size) - len(table)
        if need > len(self._free) + len(self._evictable):
            raise MemoryError(
                f"paged cache exhausted: need {need} pages, "
                f"{len(self._free)} free + {len(self._evictable)} "
                f"evictable")
        if need > 0 and self._byte_budget is not None \
                and self._kv_quant == "pressure":
            # byte-budget admission: new pages land full precision; if
            # that would breach the budget, reclaim bytes by compacting
            # parked LRU pages to int8 FIRST (compaction before
            # shedding — nothing is forgotten). Feasibility is checked
            # before any mutation so MemoryError still mutates nothing.
            # (Conservative: page-count evictions the loop below may do
            # would free more bytes, but refusing early is deterministic
            # and never over-admits.)
            fp, q = self._page_bytes
            projected = self.stored_bytes() + need * fp
            over = projected - self._byte_budget
            if over > 0:
                save = fp - q
                n_compact = -(-over // save) if save > 0 else 0
                cands = self.compact_candidates()
                if save <= 0 or n_compact > len(cands):
                    raise MemoryError(
                        f"paged cache byte budget exhausted: "
                        f"{projected} stored bytes projected > "
                        f"{self._byte_budget} budget and only "
                        f"{len(cands)} compactable pages")
                self.compact_evictable(max_pages=n_compact)
        for _ in range(max(0, need)):
            if not self._free:
                self._evict_lru()
            p = self._free.pop()
            self._refs[p] = 1
            table.append(p)
        return table

    def _evict_lru(self):
        """Reclaim ONE evictable page onto the free list: the least-
        recently-parked page with no LIVE child key (leaf-first). The
        chain invariant — an acquirer always holds a page's parents,
        so refs(parent) >= refs(child) — means an evictable page's
        children are evictable too: a leaf always exists and parents
        are never reclaimed before their children."""
        for p in self._evictable:
            kids = self._children.get(p)
            if kids and any(k in self._prefix for k in kids):
                continue  # still a parent of live keys: not a leaf
            del self._evictable[p]
            if self._arena is not None:
                self._try_spill(p)  # park content host-side BEFORE the
                # prefix identity (and the device id) dies below
            self._drop_keys(p)
            self._quant.discard(p)  # tier dies with the id: a recycled
            # page must never read stale int8 content
            self._stats["evictions"] += 1
            self._free.append(p)
            return
        raise MemoryError("no evictable leaf page")  # unreachable by
        # the chain invariant (kept as a loud guard, not a code path)

    def _drop_keys(self, p):
        """Forget page ``p``'s prefix identity before its id recycles:
        its own key, its membership in the parent's child set, and —
        the wrong-context-KV hazard — every key chained THROUGH it
        (a future sequence must never match stale children under the
        recycled id and share unrelated K/V)."""
        key = self._page_key.pop(p, None)
        if key is not None:
            self._prefix.pop(key, None)
            sibs = self._children.get(key[0])
            if sibs is not None:
                sibs.discard(key)
                if not sibs:
                    self._children.pop(key[0], None)
        for ck in self._children.pop(p, ()):
            page_c = self._prefix.pop(ck, None)
            if page_c is not None \
                    and self._page_key.get(page_c) == ck:
                self._page_key.pop(page_c, None)

    def acquire_prefix(self, seq_id, tokens) -> int:
        """Match ``tokens`` against cached FULL prompt pages; matched
        pages are SHARED into seq_id's table (refcounted) and the
        number of cached tokens (a page multiple) is returned — the
        prefill can resume past them (for a BATCHED prefill, resume at
        the MINIMUM cached count across the batch). Call BEFORE
        allocate(); if allocate() then raises MemoryError, call
        free(seq_id) before retrying or requeueing, or the shared
        refcounts leak."""
        if seq_id in self.tables:
            raise ValueError(
                f"acquire_prefix: {seq_id!r} already holds pages — "
                "free() it first (e.g. after a failed allocate)")
        table = self.tables.setdefault(seq_id, [])
        n = 0
        for page in self._chain(tokens):
            if page in self._evictable:
                del self._evictable[page]  # revival: LRU -> resident
            self._refs[page] = self._refs.get(page, 0) + 1
            table.append(page)
            n += self.page_size
        self._stats["hit_tokens"] += n
        self._stats["lookup_tokens"] += \
            (len(tokens) // self.page_size) * self.page_size
        # write()/decode append after the cached prefix, never inside it
        self.lengths[seq_id] = n
        return n

    def rollback_acquire(self, seq_id, tokens):
        """Leak-proof admit rollback for acquire_prefix -> failed
        allocate: free ``seq_id`` (shared refs released, revived pages
        re-parked evictable) AND unwind the hit/lookup stats the
        acquire recorded — a rolled-back admit was never served from
        cache, and double counting would inflate hit_rate exactly
        under the pool pressure blocked waves retry in. Valid only
        while the table still holds ONLY acquired pages (allocate
        failed without mutating)."""
        n_cached = len(self.tables.get(seq_id, ())) * self.page_size
        self.free(seq_id)
        self._stats["hit_tokens"] -= n_cached
        self._stats["lookup_tokens"] -= \
            (len(tokens) // self.page_size) * self.page_size

    def _chain(self, tokens):
        """Walk the published chain for ``tokens`` from the root,
        yielding each matched page — the ONE matcher under both
        acquire_prefix and match_prefix, so acquisition and admission
        pricing can never disagree on what the cache serves."""
        parent = 0
        n = 0
        ps = self.page_size
        while n + ps <= len(tokens):
            page = self._prefix.get(
                (parent, tuple(int(t) for t in tokens[n:n + ps])))
            if page is None:
                return
            yield page
            parent = page
            n += ps

    def match_prefix(self, tokens) -> int:
        """Non-acquiring probe: how many leading tokens of ``tokens``
        the cache could serve right now (a page multiple). No refcount,
        LRU, or stats mutation — safe for a scheduler to call per
        admission turn to price prefill work before committing."""
        return sum(self.page_size for _ in self._chain(tokens))

    def register_prefix(self, seq_id, tokens):
        """Publish seq_id's FULL prompt pages (now holding real K/V) for
        sharing. Call after the prompt's prefill wrote its pages."""
        table = self.tables.get(seq_id, [])
        parent = 0
        ps = self.page_size
        for i in range(len(tokens) // ps):
            key = (parent, tuple(int(t) for t in tokens[i * ps:(i + 1)
                                                        * ps]))
            page = table[i]
            existing = self._prefix.get(key)
            if existing is None:
                self._prefix[key] = page
                self._page_key[page] = key
                # root (parent == 0) keys are tracked too: _children is
                # the leaf test's reverse index as well as the stale-key
                # invalidator, so EVERY published key must sit under its
                # parent (page 0 is never recycled, but its child set
                # must shrink as root keys die or it leaks forever)
                self._children.setdefault(parent, set()).add(key)
            parent = self._prefix[key]

    def write(self, seq_id, k_new, v_new):
        """Append (Hkv, T, D) keys/values for seq_id; returns the
        updated (k_pages, v_pages) pool arrays (also stored on self —
        each update is a functional dynamic slice per page)."""
        T = k_new.shape[1]
        start = self.lengths.get(seq_id, 0)
        self.allocate(seq_id, start + T)
        table = self.tables[seq_id]
        ps = self.page_size
        written = 0
        while written < T:
            pos = start + written
            page = table[pos // ps]
            off = pos % ps
            n = min(ps - off, T - written)  # chunk ends at a page edge
            self.k_pages = jax.lax.dynamic_update_slice(
                self.k_pages, k_new[:, None, written:written + n].astype(
                    self.k_pages.dtype), (0, page, off, 0))
            self.v_pages = jax.lax.dynamic_update_slice(
                self.v_pages, v_new[:, None, written:written + n].astype(
                    self.v_pages.dtype), (0, page, off, 0))
            written += n
        self.lengths[seq_id] = start + T
        return self.k_pages, self.v_pages

    def free(self, seq_id):
        for p in self.tables.pop(seq_id, []):
            rc = self._refs.get(p, 1) - 1
            if rc <= 0:
                self._refs.pop(p, None)
                if p in self._page_key:
                    # retention: a PUBLISHED page outlives its last
                    # holder — park it in the evictable LRU pool with
                    # its key live, so a recurring prefix revives it
                    # instead of re-prefilling; allocate() reclaims it
                    # leaf-first only under free-list pressure
                    self._evictable[p] = True
                else:
                    self._drop_keys(p)  # stale-chain invalidation for
                    # the recycled id (unpublished pages normally have
                    # no keys; kept defensive)
                    self._quant.discard(p)
                    self._free.append(p)
            else:
                self._refs[p] = rc
        self.lengths.pop(seq_id, None)

    def purge(self):
        """Crash/abort teardown: the pool is GONE, not drained. Every
        sequence's pages are released, every RETAINED (evictable) page
        is reclaimed and every prefix key dropped — unlike ``free()``,
        nothing survives into the retention LRU, because a crashed
        replica's K/V content cannot be trusted — and the pool's
        ``epoch`` is bumped so no later sequence can ever be served
        pages written before the purge. Leaves the census balanced:
        0 resident, 0 evictable, every usable page free. (No per-page
        ``_drop_keys`` walk: the whole key space is wiped below.)"""
        n_pages = int(self.k_pages.shape[1])
        self.tables.clear()
        self.lengths.clear()
        self._refs.clear()
        self._evictable.clear()
        self._prefix.clear()
        self._page_key.clear()
        self._children.clear()
        self._quant.clear()  # both tiers go: pre-purge int8 content is
        # as untrusted as the full-precision pages
        self._free = list(range(n_pages - 1, 0, -1))
        if self._arena is not None:
            # the host tier dies with the pool: pre-purge spilled
            # content is exactly as untrusted as pre-purge device
            # pages (the epoch guard below would refuse it anyway —
            # dropping keeps the arena census honest about capacity)
            for key in self._spilled:
                self._arena.drop(key)
            self._spilled.clear()
        self.epoch += 1

    def export_chain(self, seq_id, n_tokens: int):
        """The page ids holding ``seq_id``'s first ``n_tokens``
        tokens, in chain order — what a disaggregated serving handoff
        exports (the pages beyond — decode slack the allocation
        reserved — stay behind and are freed with the sequence).
        Raises on an unknown sequence or a chain shorter than the
        asked-for tokens: exporting a hole would hand the importer
        unrelated K/V."""
        table = self.tables.get(seq_id)
        if table is None:
            raise KeyError(f"export_chain: unknown sequence "
                           f"{seq_id!r}")
        need = -(-int(n_tokens) // self.page_size)
        if need > len(table):
            raise ValueError(
                f"export_chain: {seq_id!r} holds {len(table)} pages, "
                f"{need} needed for {n_tokens} tokens")
        return list(table[:need])

    def populations(self) -> Tuple[int, int, int]:
        """The census populations (resident, evictable, free) — the
        counts ``census_ok`` balances against capacity and the cost
        ledger's occupancy sampler integrates per turn."""
        return len(self._refs), len(self._evictable), len(self._free)

    def page_holders(self) -> Dict[int, List[str]]:
        """page -> sorted holder seq_ids, from the live tables — the
        attribution view of the resident tier (a shared prefix page
        lists every sharer; refcounts mirror these memberships, which
        the ledger's occupancy audit cross-checks)."""
        holders: Dict[int, List[str]] = {}
        for sid in sorted(self.tables):
            for p in self.tables[sid]:
                holders.setdefault(p, []).append(sid)
        return holders

    def census_ok(self) -> bool:
        """The accounting invariant in one place: every usable page
        (page 0 is reserved padding) is exactly one of resident /
        evictable / free. The serving engine samples this each turn;
        the serving_prefix bench gate fails if it ever broke."""
        balanced = obs_ledger.census_balanced(
            int(self.k_pages.shape[1]) - 1, *self.populations())
        # the quantized tier is an overlay, never a fourth state: every
        # quantized page must still be occupied
        tier_ok = obs_ledger.overlay_contained(
            self._quant, self._refs, self._evictable)
        if self._arena is not None:
            # the host tier extends the census: spilled is a distinct
            # state (spill != leak, like retention != leak) — after
            # reconciling arena-side LRU deaths, every spilled key
            # must be live in the arena, and the arena's own
            # pinned+evictable+free conservation must hold
            self._prune_spilled()
            if not self._arena.census_ok():
                return False
            if any(k not in self._arena for k in self._spilled):
                return False
        return balanced and tier_ok

    def cache_stats(self) -> dict:
        """Prefix-cache accounting: cumulative hit/lookup tokens and
        evictions plus the live page census. The census satisfies
        ``resident + evictable + free == n_pages - 1`` at all times
        (page 0 is the reserved padding page) — the invariant the
        serving bench gate checks."""
        hit = self._stats["hit_tokens"]
        lookup = self._stats["lookup_tokens"]
        out = {
            "n_pages": int(self.k_pages.shape[1]) - 1,
            "resident_pages": len(self._refs),
            "evictable_pages": len(self._evictable),
            "free_pages": len(self._free),
            "hit_tokens": hit,
            "lookup_tokens": lookup,
            "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            "evictions": self._stats["evictions"],
        }
        if self._pool_bytes is not None:
            # only when noted (a sharded serving pool): unsharded runs
            # keep the pre-TP dict byte-for-byte
            out["bytes_total"] = self._pool_bytes[0]
            out["bytes_per_device"] = self._pool_bytes[1]
        if self._kv_quant is not None:
            # kv_quant census bucket — present only when the tier is
            # armed (kv_quant=None keeps the dict byte-identical).
            # always-int8 stores every occupied page quantized; pressure
            # counts the compacted overlay.
            occupied = len(self._refs) + len(self._evictable)
            out["quantized_pages"] = (occupied
                                      if self._kv_quant == "int8"
                                      else len(self._quant))
            out["compactions"] = self._stats["compactions"]
            sb = self.stored_bytes()
            if sb is not None:
                out["stored_bytes"] = sb
        if self._arena is not None:
            # hostmem census bucket — present only when the tier is
            # armed (hostmem=None keeps the dict byte-identical)
            self._prune_spilled()
            out["spilled_pages"] = len(self._spilled)
            out["spills"] = self._spill_stats["spills"]
            out["pageins"] = self._spill_stats["pageins"]
            out["spill_refusals"] = self._spill_stats["spill_refusals"]
        return out

    def batch_views(self, seq_ids):
        """(page_tables (B, max_pages), seq_lens (B,)) padded with the
        reserved page 0."""
        import numpy as np
        tables = [self.tables[s] for s in seq_ids]
        width = max((len(t) for t in tables), default=1)
        pt = np.zeros((len(seq_ids), width), np.int32)
        for i, t in enumerate(tables):
            pt[i, :len(t)] = t
        sl = np.asarray([self.lengths[s] for s in seq_ids], np.int32)
        return jnp.asarray(pt), jnp.asarray(sl)


# --- prefill over pages (chunked-prefill attention) ------------------------

def paged_prefill_attention(q, k_pages, v_pages, page_tables, seq_lens,
                            q_start, sm_scale=None, k_scales=None,
                            v_scales=None):
    """Causal attention of a C-token query chunk against the paged pool
    (the chunk's own K/V must already be written to its pages).

    q (B, Hq, C, D); pools as in paged_attention; q_start: scalar
    absolute position of the chunk's first token (shared across the
    left-aligned batch). Returns (B, Hq, C, D). The chunk=C case of the
    shared paged kernel; pages entirely beyond start+C or the sequence
    length are skipped.
    """
    B, Hq, C, D = q.shape
    Hkv = k_pages.shape[0]
    if Hq % Hkv:
        raise ValueError(f"query heads {Hq} not a multiple of kv heads "
                         f"{Hkv}")
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    starts = jnp.full((B,), q_start, jnp.int32)
    out = _paged_call(q.reshape(B, Hkv, G * C, D), k_pages, v_pages,
                      page_tables, jnp.asarray(seq_lens, jnp.int32),
                      starts, C, sm_scale, k_scales, v_scales)
    return out.reshape(B, Hq, C, D)
