"""Flash attention Pallas TPU kernel.

TPU-native replacement for the reference's fused CUDA attention
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h — which
materializes the full O(s^2) score matrix). This kernel implements the
online-softmax streaming algorithm: scores never leave VMEM, HBM traffic is
O(s*d), and the MXU sees back-to-back (bq x d)@(d x bk) and (bq x bk)@(bk x d)
matmuls.

Design notes (measured on v5e at B=8, H=12, S=2048, D=128, bf16):
- K/V stay RESIDENT in VMEM for the whole kv walk (full-seq BlockSpec) and
  the walk is a fori_loop — measured faster (337ms train step) than
  streaming kv blocks through an innermost grid dimension with scratch
  accumulators (366ms): resident K/V costs zero DMA inside the loop, and at
  S<=16k the footprint (S*D*2B per tensor) fits VMEM comfortably. Longer
  sequences should shard over the 'sep' mesh axis (ring attention) rather
  than stream here.
- Matmul operands stay in their storage dtype (bf16 runs the MXU at full
  rate; f32 at half), accumulating in f32 via preferred_element_type.
- Softmax runs in the exp2 domain with sm_scale*log2e folded into q (or k)
  once per kernel invocation; lse is stored in the natural-log domain.
- Masking every live block measured faster than lax.cond diagonal-only
  masking (cond defeats Mosaic's loop pipelining).

Layout: (batch, heads, seq, head_dim). Forward saves per-row logsumexp for
the backward pass; backward recomputes block scores (flash-style) to form
dQ/dK/dV without the s^2 buffer.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # CPU backend (tests / sim meshes) runs kernels in interpreter mode
    import jax
    return jax.default_backend() == "cpu"

DEFAULT_BLOCK_Q = None  # auto: largest of 512/256/128 dividing the seq
DEFAULT_BLOCK_K = None
NEG_INF = -1e30
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def _pick_block(seq_len: int) -> int:
    # Measured on v5e at (B8,H12,S2048,D128) fwd+bwd: 512 blocks run 11.6ms
    # vs 18.4ms at the MXU-tile minimum of 128 — bigger blocks amortize the
    # grid/loop overhead and keep the MXU busy; 1024 is no faster and eats
    # VMEM headroom.
    for cand in (512, 256, 128):
        if seq_len % cand == 0:
            return cand
    # Correctness fallback for non-128-multiple sequences: the block MUST
    # divide seq_len (grid steps would otherwise skip output rows / kv
    # positions) and stay sublane-aligned for Mosaic (multiple of 8) —
    # including seq_len <= 128, where returning seq_len verbatim would hand
    # Mosaic an unaligned sublane count (e.g. S=100).
    for cand in range(min(128, seq_len), 7, -1):
        if seq_len % cand == 0 and cand % 8 == 0:
            return cand
    raise ValueError(
        f"flash_attention: no sublane-aligned block divides seq_len="
        f"{seq_len}; pad the sequence to a multiple of 128")


def _resolve_blocks(Sq, Sk, block_q, block_k):
    return (block_q or _pick_block(Sq), block_k or _pick_block(Sk))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]  # (block_q, d)
    # fold sm_scale*log2e into q once: scores leave the MXU already in the
    # exp2 domain with no per-block rescale
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    num_kv = kv_len // block_k
    if causal:
        # only blocks at or before the diagonal contribute
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_live, body, (m, l, acc))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse is saved in the natural-log domain (bwd converts back)
    lse_ref[0] = (LN2 * m + jnp.log(l_safe))[:, None].astype(jnp.float32)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    q2 = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    do = do_ref[0]
    lse2 = lse_ref[0, :, 0] * LOG2E  # exp2-domain logsumexp
    delta = delta_ref[0, :, 0]
    dq = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    num_kv = kv_len // block_k
    if causal:
        num_live = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                               num_kv)
    else:
        num_live = num_kv

    def body(kj, dq):
        k = k_ref[0, pl.dslice(kj * block_k, block_k)]
        v = v_ref[0, pl.dslice(kj * block_k, block_k)]
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_live, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k,
                    q_len):
    kj = pl.program_id(1)
    k = k_ref[0]  # (block_k, d)
    v = v_ref[0]
    # fold sm_scale*log2e into k once (dk accumulation uses unscaled q)
    k2 = (k.astype(jnp.float32) * (sm_scale * LOG2E)).astype(k.dtype)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    num_q = q_len // block_q
    if causal:
        first_live = (kj * block_k) // block_q
    else:
        first_live = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(qi * block_q, block_q)]
        do = do_ref[0, pl.dslice(qi * block_q, block_q)]
        lse2 = lse_ref[0, pl.dslice(qi * block_q, block_q), 0] * LOG2E
        delta = delta_ref[0, pl.dslice(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k2, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])  # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(first_live, num_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_attention blocks ({block_q},{block_k}) must divide "
            f"seq lens ({Sq},{Sk}); pass block_q/block_k=None to auto-pick")
    bh = B * H
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    grid = (bh, Sq // block_q)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=Sk)
    out, lse = functools.partial(pl.pallas_call, interpret=_interpret())(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((bh, Sq, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr)
    return out.reshape(B, H, Sq, D), lse[..., 0].reshape(B, H, Sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    bwd_block_q=None, bwd_block_k=None):
    """q/k/v: (batch, heads, seq, head_dim). Returns same shape as q.

    ``bwd_block_q``/``bwd_block_k`` tile the two backward kernels
    independently of the forward (None = same as forward). The backward
    walks the opposite operand full-length per block (dq walks K/V,
    dk/dv walks Q), so its VMEM/pipelining optimum need not match the
    forward's — tools/flash_bwd_sweep.py measures the grid on chip.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k = _resolve_blocks(q.shape[2], k.shape[2],
                                       block_q, block_k)
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
            bwd_block_q, bwd_block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k = _resolve_blocks(q.shape[2], k.shape[2],
                                       block_q, block_k)
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k,
            res, do):
    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k = _resolve_blocks(
        q.shape[2], k.shape[2],
        bwd_block_q or block_q, bwd_block_k or block_k)
    # explicit bwd blocks skip the fwd path's validation; a non-dividing
    # block would silently leave output rows unwritten (grid truncation)
    if q.shape[2] % block_q or k.shape[2] % block_k:
        raise ValueError(
            f"flash_attention backward blocks ({block_q}, {block_k}) must "
            f"divide seq lens ({q.shape[2]}, {k.shape[2]})")
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bh = B * H
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, Sq, 1)
    qr = q.reshape(bh, Sq, D)
    kr = k.reshape(bh, Sk, D)
    vr = v.reshape(bh, Sk, D)
    dor = do.reshape(bh, Sq, D)
    lser = lse.reshape(bh, Sq, 1)

    dq = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=Sk),
        grid=(bh, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, Sq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    dk, dv = functools.partial(pl.pallas_call, interpret=_interpret())(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, q_len=Sq),
        grid=(bh, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((bh, Sk, D), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, dor, lser, delta)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
